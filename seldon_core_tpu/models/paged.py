"""Paged KV-cache + continuous batching for autoregressive serving.

The contiguous cache in :mod:`seldon_core_tpu.models.generate` allocates
``batch x max_len`` K/V slots per request batch and requires every
prompt in a batch to share one length.  This module replaces that with
the memory model long-running generation services need (the reference
serving stack has no generation path at all — this extends the
framework the direction its GPU successors went):

* **Paged pool** — K/V live in one shared pool of fixed-size pages
  ``(layers, num_pages, page_size, heads, head_dim)``; each stream owns
  a *block table* mapping its logical positions to pages.  HBM scales
  with tokens actually generated, not ``slots x max_len``.
* **Continuous batching** — streams join and leave between decode
  chunks; one compiled decode program of static shape ``(max_slots,)``
  serves every mix of prompt lengths, sampling settings and
  ``max_new_tokens``.  Finished slots free their pages immediately and
  the next queued request takes over the slot — no head-of-line
  blocking on the longest generation in a batch.
* **Static shapes throughout** — page reads are one gather, writes one
  scatter; EOS/stall handling is mask-based; the per-chunk inner loop
  is a ``lax.scan`` with sampling on device, so ``steps_per_call``
  tokens cost one host round-trip.

``PagedTransformerLM`` mirrors :class:`TransformerLM`'s parameter tree
exactly (same module names in the same order), so a trained
TransformerLM checkpoint drives paged decoding unchanged — tested by
structural equality in tests/test_paged.py.

Page 0 is reserved as a *trash page*: writes for masked-out lanes
(padding, finished or stalled slots) are redirected there and no block
table ever legitimately reads past its stream's length, so scatters
need no dynamic control flow.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def paged_kernel_mode() -> str:
    """The ``SELDON_TPU_PAGED_KERNEL`` env value ("0" | "1" | "auto" |
    "force") — the ONE place its vocabulary lives.  The block's kernel
    gate, the pool-layout decision (:func:`pool_is_flat`) and the
    engine's chunk-impl auto-select all read through here, so a new
    mode string cannot leave the three silently disagreeing.  Since the
    r18 default flip the unset value is "auto": the kernel lane is the
    production decode path on single-chip TPU backends, and "0"
    restores the XLA gather lane byte-for-byte."""
    return _knobs.raw("SELDON_TPU_PAGED_KERNEL", "auto")


def paged_kernel_explicit(mode: Optional[str] = None) -> bool:
    """True when the operator EXPLICITLY opted in ("1" | "force") —
    the modes whose ineligibility deserves a WARN.  "auto" degrading to
    the gather lane is a default resolving, not a broken request, so it
    stays silent (the ``kernel_active`` gauge reports which lane won)."""
    return (mode if mode is not None else paged_kernel_mode()) in ("1", "force")


def paged_kernel_requested(mode: Optional[str] = None) -> bool:
    """Whether this process WANTS the pallas decode kernel: an explicit
    "1"/"force", or the "auto" default resolving on a TPU backend
    (off-TPU "auto" means the gather lane, so CPU/GPU processes keep
    the historical flat pool and programs byte-for-byte)."""
    mode = mode if mode is not None else paged_kernel_mode()
    if mode in ("1", "force"):
        return True
    if mode == "auto":
        import jax

        return jax.default_backend() == "tpu"
    return False


def paged_kernel_static_eligible(mode: str, mesh_absent: bool, dtype) -> bool:
    """The STATIC half of the pallas decode-kernel gate, shared by the
    block's trace-time ``use_kernel`` and the engine's chunk-impl
    auto-select so the two cannot drift: requested by env (explicitly
    or via the "auto" default on TPU), no TP mesh (GSPMD can't
    partition the pallas call), a bf16 or f32 pool (f32 is the
    exactness lane the kernel-parity tests pin), and a TPU backend
    unless forced (interpret mode).  The block adds its trace-local
    terms (decode step, split pool layout) on top."""
    import jax
    import jax.numpy as jnp

    return (
        paged_kernel_requested(mode)
        and mesh_absent
        and dtype in (jnp.bfloat16, jnp.float32)
        and (mode == "force" or jax.default_backend() == "tpu")
    )


def paged_kv_dtype_mode() -> str:
    """The ``SELDON_TPU_KV_DTYPE`` env value ("bf16" | "int8") — int8
    stores KV pages quantised with one f32 scale per page per k/v in a
    sibling ``(layers, num_pages)`` scale table (r18).  Anything other
    than "int8" means the pool stores the engine dtype natively."""
    return _knobs.raw("SELDON_TPU_KV_DTYPE", "bf16") or "bf16"

from seldon_core_tpu.models.generate import _buckets_for
from seldon_core_tpu.runtime import knobs as _knobs
from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent
from seldon_core_tpu.utils import faults as _faults
from seldon_core_tpu.utils import telemetry as _telemetry
from seldon_core_tpu.utils.deadlines import deadline_exceeded


# ---------------------------------------------------------------------------
# flax module — parameter-compatible with TransformerLM
# ---------------------------------------------------------------------------


def _build_modules():
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    def _dense(precision, features, dtype, name):
        """Projection factory: ``precision="w8a8"`` swaps every decode
        projection (qkv, attn_proj, mlp_in/out, the unembed head) for
        the int8×int8 layer (ops/w8a8.py) — SAME params tree as
        nn.Dense, so the TransformerLM checkpoint-parity invariant
        holds across precisions.  The engine passes only ``params`` to
        apply, so activation scales are dynamic PER-TOKEN (abs-max over
        d only — never the slot axis, so one stream's quantisation grid
        cannot depend on co-scheduled traffic, and the width-1 decode
        and width-(k+1) speculative-verify programs quantise each token
        identically: greedy exactness holds, tested)."""
        if precision == "w8a8":
            from seldon_core_tpu.ops.w8a8 import W8A8Dense

            return W8A8Dense(features=features, dtype=dtype, name=name)
        return nn.Dense(features, dtype=dtype, name=name)

    class PagedTransformerBlock(nn.Module):
        """TransformerBlock whose attention reads a paged K/V pool.

        Returns this call's K/V instead of mutating a flax collection —
        the caller owns the scatter (functional state, donate-friendly).
        """

        num_heads: int
        mlp_ratio: int = 4
        dtype: Any = jnp.bfloat16
        precision: str = "bf16"  # "w8a8": int8×int8 projections
        # decode fast path (pallas flash-decoding) — the engine turns
        # this off under tensor-parallel meshes: GSPMD cannot partition
        # a pallas_call whose BlockSpecs span the full heads axis, so a
        # heads-sharded pool would all-gather per layer per step
        decode_kernel: bool = True

        @nn.compact
        def __call__(self, x, pk, pv, block_tables, lengths,
                     lora=None, adapter_idx=None, kv_scales=None):
            # x: (B, L, d)  pk/pv: (num_pages, ps, h, hd) split, or the
            # r5-default flat (num_pages, ps, d) — the gather below
            # reshapes either to (B, cache_len, h, hd), and the kernel
            # gate keys on pk.ndim (the pallas BlockSpecs need split)
            # block_tables: (B, P) int32, or a TUPLE of per-bucket
            # tables ((B0, P0), (B1, P1), ...) with sum(Bb) == B — the
            # r6 length-bucketed gather: lanes arrive bucket-sorted and
            # each bucket gathers/attends at its own static page
            # horizon (dense projections stay full-batch)
            # lengths: (B,) tokens in cache
            # lora/adapter_idx (r16): slot-granular low-rank factor
            # pools + a TRACED per-lane slot id — every projection adds
            # the gathered grouped-matmul delta (ops/lora.py), so a
            # wave mixing K adapters is ONE program; lora=None is the
            # byte-identical adapter-off path (no new ops traced)
            # kv_scales (r18): ``(sk, sv)`` per-page f32 ``(num_pages,)``
            # scale vectors for an int8 pool — both attention lanes
            # dequantise through them (the kernel in-register, the
            # gather right after the page fetch); None means the pool
            # stores self.dtype natively and the trace is byte-identical
            # to r17
            tables = (
                tuple(block_tables)
                if isinstance(block_tables, (tuple, list))
                else (block_tables,)
            )
            d_model = x.shape[-1]
            heads = self.num_heads
            head_dim = d_model // heads
            batch, seg_len = x.shape[:2]

            # since the r18 default flip ("auto") this is the PRODUCTION
            # decode lane on single-chip TPU backends — the r4 gather-
            # vs-kernel measurements that kept it opt-in predate the
            # streaming DMA rework; SELDON_TPU_PAGED_KERNEL=0 restores
            # the XLA gather lane byte-for-byte
            use_kernel = (
                seg_len == 1
                # decode_kernel=False is how the engine encodes a TP
                # mesh; the static terms (env, dtype, backend) live in
                # the shared predicate the chunk auto-select also uses
                and self.decode_kernel
                # the kernels' BlockSpecs index the SPLIT (pages, ps,
                # h, hd) layout — a flat pool (the r5 default) takes
                # the gather path regardless of the env opt-in
                and pk.ndim == 4
                and paged_kernel_static_eligible(
                    paged_kernel_mode(), True, self.dtype
                )
            )
            # r18: the per-lane qkv LoRA BGMV folds INTO the stream
            # kernel launch (the slot-index gather rides the scalar
            # prefetch next to the block tables) — one fused program
            # instead of kernel + two einsums.  Sound without further
            # care because this model applies no RoPE between the qkv
            # projection and attention (learned positional embeddings
            # add at the LM level), so the low-rank delta is linear in
            # the projection output.  Grid impl keeps the outside-
            # kernel einsum path.
            fold_qkv = False
            if use_kernel and lora is not None and "qkv" in lora:
                from seldon_core_tpu.ops.kernels import paged_kernel_impl

                fold_qkv = paged_kernel_impl(heads, head_dim) == "stream"

            def _proj(name, features, inp):
                out = _dense(self.precision, features, self.dtype, name)(inp)
                if lora is not None and name in lora and not (
                    fold_qkv and name == "qkv"
                ):
                    from seldon_core_tpu.ops.lora import lora_delta

                    a_f, b_f = lora[name]
                    out = out + lora_delta(inp, a_f, b_f, adapter_idx).astype(
                        out.dtype
                    )
                return out

            y = nn.LayerNorm(dtype=jnp.float32)(x)
            qkv = _proj("qkv", 3 * d_model, y)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            shape = (batch, seg_len, heads, head_dim)
            q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)

            scale = 1.0 / jnp.sqrt(head_dim).astype(q.dtype)
            if use_kernel:
                # pallas flash-decoding over the paged pool
                # (ops/kernels.py paged_attention_decode): pages stream
                # HBM->VMEM indexed by the block table; the
                # (B, P, ps, h, hd) gathered copy below never
                # materialises.  The current token merges via the flash
                # rule.  Under the bucketed gather each bucket is one
                # kernel call at its own table width — the kernel's
                # per-lane page loop is already length-bounded, so
                # bucketing only trims the BlockSpec grid.  NUMERIC
                # REGIME: the kernel scores in f32 where the gather path
                # scores in bf16, so on hardware a kernel-decode engine
                # and a gather-path engine (e.g. a speculative verify
                # program) can break argmax ties differently — each lane
                # is deterministic, the f32 exactness lanes always use
                # the gather path, and SELDON_TPU_PAGED_KERNEL=0
                # restores one regime when cross-lane bit-equality
                # matters more than speed.
                from seldon_core_tpu.ops.kernels import paged_attention_decode

                if fold_qkv:
                    a_f, b_fact = lora["qkv"]
                    # the kernel DMAs one lane's (r, D) factor rows; the
                    # 128-aligned d minor wants A TRANSPOSED
                    a_T = jnp.swapaxes(a_f, -1, -2)   # (slots, r, d)
                    q_scale_f = float(head_dim) ** -0.5
                outs = []
                deltas = []
                off = 0
                for tb in tables:
                    nb = tb.shape[0]
                    sl = slice(off, off + nb)
                    q1 = (q[sl] * scale)[:, 0]  # (nb, h, hd)
                    if fold_qkv:
                        acc, m, l, delta = paged_attention_decode(
                            q1, pk, pv, tb, lengths[sl],
                            page_size=pk.shape[1], kv_scales=kv_scales,
                            lora=(y[sl][:, 0], a_T, b_fact,
                                  adapter_idx[sl], q_scale_f),
                        )
                        deltas.append(delta)
                        dq, dk, dv = jnp.split(delta, 3, axis=-1)
                        q_self = (
                            q1.astype(jnp.float32)
                            + q_scale_f * dq.reshape(nb, heads, head_dim)
                        )
                        k_self = (
                            k[sl][:, 0].astype(jnp.float32)
                            + dk.reshape(nb, heads, head_dim)
                        )
                        v_self = (
                            v[sl][:, 0].astype(jnp.float32)
                            + dv.reshape(nb, heads, head_dim)
                        )
                    else:
                        acc, m, l = paged_attention_decode(
                            q1, pk, pv, tb, lengths[sl],
                            page_size=pk.shape[1], kv_scales=kv_scales,
                        )
                        q_self = q1.astype(jnp.float32)
                        k_self = k[sl][:, 0].astype(jnp.float32)
                        v_self = v[sl][:, 0].astype(jnp.float32)
                    s_self = jnp.einsum("bhd,bhd->bh", q_self, k_self)
                    m2 = jnp.maximum(m, s_self)
                    alpha = jnp.exp(m - m2)
                    w_self = jnp.exp(s_self - m2)
                    l2 = l * alpha + w_self
                    out_b = (
                        acc * alpha[..., None]
                        + v_self * w_self[..., None]
                    ) / l2[..., None]
                    outs.append(out_b[:, None].astype(self.dtype))
                    off += nb
                attn = (
                    outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
                )
                attn = attn.reshape(batch, seg_len, d_model)
                if fold_qkv:
                    # fold the kernel's raw delta into the k/v this call
                    # returns — the caller's pool write must store the
                    # ADAPTED keys/values, same as the einsum path
                    delta_all = (
                        deltas[0] if len(deltas) == 1
                        else jnp.concatenate(deltas, axis=0)
                    )
                    _, dk_all, dv_all = jnp.split(delta_all, 3, axis=-1)
                    k = (
                        k.astype(jnp.float32)
                        + dk_all.reshape(batch, 1, heads, head_dim)
                    ).astype(self.dtype)
                    v = (
                        v.astype(jnp.float32)
                        + dv_all.reshape(batch, 1, heads, head_dim)
                    ).astype(self.dtype)
            else:
                # gather path — same arithmetic as
                # TransformerBlock._cached_attention: bf16 scores
                # masked with finfo.min, f32 softmax; one gather +
                # attention per bucket, each at its own static width
                outs = []
                off = 0
                for tb in tables:
                    nb = tb.shape[0]
                    sl = slice(off, off + nb)
                    gk = pk[tb]  # (nb, P, ps, h, hd) split / (nb, P, ps, d) flat
                    gv = pv[tb]
                    pages_per, page_size = gk.shape[1], gk.shape[2]
                    cache_len = pages_per * page_size
                    if kv_scales is not None:
                        # int8 pool: dequantise right after the page
                        # fetch — one f32 scale per gathered page,
                        # broadcast over its (ps, ...) token block
                        sk_l, sv_l = kv_scales
                        bshape = (nb, pages_per) + (1,) * (gk.ndim - 2)
                        gk = (
                            gk.astype(jnp.float32) * sk_l[tb].reshape(bshape)
                        ).astype(self.dtype)
                        gv = (
                            gv.astype(jnp.float32) * sv_l[tb].reshape(bshape)
                        ).astype(self.dtype)
                    gk = gk.reshape(nb, cache_len, heads, head_dim)
                    gv = gv.reshape(nb, cache_len, heads, head_dim)

                    sc = jnp.einsum("bqhd,bkhd->bhqk", q[sl] * scale, gk)
                    ss = jnp.einsum("bqhd,bkhd->bhqk", q[sl] * scale, k[sl])
                    neg = jnp.finfo(sc.dtype).min
                    cache_mask = (
                        jnp.arange(cache_len)[None, :] < lengths[sl][:, None]
                    )  # (nb, cache_len)
                    sc = jnp.where(cache_mask[:, None, None, :], sc, neg)
                    seg_mask = (
                        jnp.arange(seg_len)[None, :]
                        <= jnp.arange(seg_len)[:, None]
                    )  # (L, L) causal within this segment
                    ss = jnp.where(seg_mask[None, None], ss, neg)
                    scores = jnp.concatenate(
                        [sc, ss], axis=-1
                    ).astype(jnp.float32)
                    weights = jax.nn.softmax(scores, axis=-1).astype(self.dtype)
                    wc, ws = weights[..., :cache_len], weights[..., cache_len:]
                    outs.append(
                        jnp.einsum("bhqk,bkhd->bqhd", wc, gv)
                        + jnp.einsum("bhqk,bkhd->bqhd", ws, v[sl])
                    )
                    off += nb
                attn = (
                    outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
                )
                attn = attn.reshape(batch, seg_len, d_model)

            x = x + _proj("attn_proj", d_model, attn)
            y = nn.LayerNorm(dtype=jnp.float32)(x)
            y = _proj("mlp_in", self.mlp_ratio * d_model, y)
            y = nn.gelu(y)
            x = x + _proj("mlp_out", d_model, y)
            return x, k, v

    class ChunkTransformerBlock(nn.Module):
        """TransformerBlock reading a pre-gathered contiguous context
        plus a step-indexed in-chunk ring — the decode-chunk fast path.

        The r5 slot-scaling probe showed the per-STEP pool gather is
        the chunk's pathology: its cost scales superlinearly with
        total gathered bytes (measured 3.2 ms/step at 64 slots ->
        18.4 ms/step at 128, 13.7x the traffic floor), and the
        gather+DUS read/write hazard on the pool adds several more
        ms/step of scheduling overhead.  This block never touches the
        pool: the caller gathers each slot's context ONCE per chunk
        into ``ctx`` (amortised over steps) and accumulates the
        chunk's own K/V in ``ring`` (written at column ``step`` —
        uniform across slots, one DUS per step).  Attention is then
        three dense einsums (ctx, ring, self) — the same token set,
        masks, and dtypes as the pool gather path.
        """

        num_heads: int
        mlp_ratio: int = 4
        dtype: Any = jnp.bfloat16
        precision: str = "bf16"  # "w8a8": int8×int8 projections

        @nn.compact
        def __call__(self, x, ctx_k, ctx_v, ring_k, ring_v, step, len0,
                     lora=None, adapter_idx=None):
            # x: (B, 1, d)   ring_k/v: (B, S, h, hd)
            # ctx_k/v: (B, C, h, hd), or a TUPLE of per-bucket buffers
            # ((B0, C0, h, hd), (B1, C1, h, hd), ...) with sum(Bb) == B —
            # the r6 length-bucketed gather: lanes arrive bucket-sorted
            # (shortest contexts first), so each bucket's context einsums
            # run at ITS OWN static width instead of every lane paying
            # the longest stream's C.  Dense work (projections, MLP,
            # embed/head in the LM) stays full-batch — only the per-lane
            # context attention splits, so there is no extra weight
            # traffic and no extra dispatch.
            # — the engine materialises the working set SPLIT even over
            # a flat-at-rest pool ("flat at rest, split in flight"; the
            # split form is what the per-step dense reads want)
            # step: scalar — ring columns < step are live
            # len0: (B,) context lengths frozen at chunk start
            if not isinstance(ctx_k, (tuple, list)):
                ctx_k, ctx_v = (ctx_k,), (ctx_v,)
            d_model = x.shape[-1]
            heads = self.num_heads
            head_dim = d_model // heads
            batch, seg_len = x.shape[:2]

            # same grouped multi-LoRA hook as PagedTransformerBlock —
            # dense work (and therefore the delta) stays full-batch,
            # only the context attention splits by bucket
            def _proj(name, features, inp):
                out = _dense(self.precision, features, self.dtype, name)(inp)
                if lora is not None and name in lora:
                    from seldon_core_tpu.ops.lora import lora_delta

                    a_f, b_f = lora[name]
                    out = out + lora_delta(inp, a_f, b_f, adapter_idx).astype(
                        out.dtype
                    )
                return out

            y = nn.LayerNorm(dtype=jnp.float32)(x)
            qkv = _proj("qkv", 3 * d_model, y)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            shape = (batch, seg_len, heads, head_dim)
            q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)
            scale = 1.0 / jnp.sqrt(head_dim).astype(q.dtype)

            S = ring_k.shape[1]
            ring_mask = jnp.arange(S) < step  # (S,) cols written so far
            neg = jnp.finfo(q.dtype).min
            outs = []
            off = 0
            for ck, cv in zip(ctx_k, ctx_v):
                nb, C = ck.shape[0], ck.shape[1]
                sl = slice(off, off + nb)
                q_b = q[sl] * scale
                sc = jnp.einsum("bqhd,bkhd->bhqk", q_b, ck)
                sr = jnp.einsum("bqhd,bkhd->bhqk", q_b, ring_k[sl])
                ss = jnp.einsum("bqhd,bkhd->bhqk", q_b, k[sl])
                ctx_mask = jnp.arange(C)[None, :] < len0[sl][:, None]  # (nb, C)
                sc = jnp.where(ctx_mask[:, None, None, :], sc, neg)
                sr = jnp.where(ring_mask[None, None, None, :], sr, neg)
                scores = jnp.concatenate(
                    [sc, sr, ss], axis=-1
                ).astype(jnp.float32)
                weights = jax.nn.softmax(scores, axis=-1).astype(self.dtype)
                wc = weights[..., :C]
                wr = weights[..., C:C + S]
                ws = weights[..., C + S:]
                outs.append(
                    jnp.einsum("bhqk,bkhd->bqhd", wc, cv)
                    + jnp.einsum("bhqk,bkhd->bqhd", wr, ring_v[sl])
                    + jnp.einsum("bhqk,bkhd->bqhd", ws, v[sl])
                )
                off += nb
            attn = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
            attn = attn.reshape(batch, seg_len, d_model)
            x = x + _proj("attn_proj", d_model, attn)
            y = nn.LayerNorm(dtype=jnp.float32)(x)
            y = _proj("mlp_in", self.mlp_ratio * d_model, y)
            y = nn.gelu(y)
            x = x + _proj("mlp_out", d_model, y)
            return x, k, v

    class ChunkTransformerLM(nn.Module):
        """PagedTransformerLM's decode-chunk twin: identical parameter
        tree (same module names per block), pool-free attention inputs.

        ``__call__(tokens, positions, ctx_k, ctx_v, ring_k, ring_v,
        step, len0)`` -> ``(logits, new_k, new_v)`` with ctx/ring
        shaped ``(layers, B, C|S, heads, head_dim)``; ``ctx_k``/
        ``ctx_v`` may instead be tuples of per-bucket buffers (the
        length-bucketed gather — see ChunkTransformerBlock).
        """

        vocab_size: int = 32_000
        d_model: int = 256
        num_layers: int = 4
        num_heads: int = 8
        max_len: int = 2048
        dtype: Any = jnp.bfloat16
        precision: str = "bf16"

        @nn.compact
        def __call__(self, tokens, positions, ctx_k, ctx_v, ring_k, ring_v,
                     step, len0, lora=None, adapter_idx=None):
            tokens = tokens.astype(jnp.int32)
            x = nn.Embed(
                self.vocab_size, self.d_model, dtype=self.dtype, name="tok_embed"
            )(tokens)
            pos = nn.Embed(
                self.max_len, self.d_model, dtype=self.dtype, name="pos_embed"
            )(positions)
            x = x + pos
            bucketed = isinstance(ctx_k, (tuple, list))
            new_k, new_v = [], []
            for i in range(self.num_layers):
                layer_ck = (
                    tuple(c[i] for c in ctx_k) if bucketed else ctx_k[i]
                )
                layer_cv = (
                    tuple(c[i] for c in ctx_v) if bucketed else ctx_v[i]
                )
                lora_i = (
                    {t: (ab[0][i], ab[1][i]) for t, ab in lora.items()}
                    if lora is not None else None
                )
                x, k, v = ChunkTransformerBlock(
                    num_heads=self.num_heads, dtype=self.dtype,
                    precision=self.precision, name=f"block_{i}"
                )(x, layer_ck, layer_cv, ring_k[i], ring_v[i], step, len0,
                  lora=lora_i, adapter_idx=adapter_idx)
                new_k.append(k)
                new_v.append(v)
            x = nn.LayerNorm(dtype=jnp.float32)(x)
            logits = _dense(self.precision, self.vocab_size, self.dtype, "head")(x)
            return logits.astype(jnp.float32), jnp.stack(new_k), jnp.stack(new_v)

    class PagedTransformerLM(nn.Module):
        """TransformerLM forward against a paged pool.

        ``__call__(tokens, positions, pages_k, pages_v, block_tables,
        lengths)`` -> ``(logits, new_k, new_v)`` where new_k/new_v are
        ``(layers, B, L, heads, head_dim)`` for the caller to scatter.
        """

        vocab_size: int = 32_000
        d_model: int = 256
        num_layers: int = 4
        num_heads: int = 8
        max_len: int = 2048
        dtype: Any = jnp.bfloat16
        precision: str = "bf16"
        decode_kernel: bool = True

        @nn.compact
        def __call__(self, tokens, positions, pages_k, pages_v, block_tables,
                     lengths, lora=None, adapter_idx=None, kv_scales=None):
            tokens = tokens.astype(jnp.int32)
            x = nn.Embed(
                self.vocab_size, self.d_model, dtype=self.dtype, name="tok_embed"
            )(tokens)
            pos = nn.Embed(
                self.max_len, self.d_model, dtype=self.dtype, name="pos_embed"
            )(positions)
            x = x + pos
            new_k, new_v = [], []
            for i in range(self.num_layers):
                lora_i = (
                    {t: (ab[0][i], ab[1][i]) for t, ab in lora.items()}
                    if lora is not None else None
                )
                scales_i = (
                    (kv_scales[0][i], kv_scales[1][i])
                    if kv_scales is not None else None
                )
                x, k, v = PagedTransformerBlock(
                    num_heads=self.num_heads, dtype=self.dtype,
                    precision=self.precision,
                    decode_kernel=self.decode_kernel, name=f"block_{i}"
                )(x, pages_k[i], pages_v[i], block_tables, lengths,
                  lora=lora_i, adapter_idx=adapter_idx, kv_scales=scales_i)
                new_k.append(k)
                new_v.append(v)
            x = nn.LayerNorm(dtype=jnp.float32)(x)
            logits = _dense(self.precision, self.vocab_size, self.dtype, "head")(x)
            return logits.astype(jnp.float32), jnp.stack(new_k), jnp.stack(new_v)

    return PagedTransformerBlock, PagedTransformerLM, ChunkTransformerLM


_MODULES: Optional[Tuple[Any, Any, Any]] = None


def get_paged_lm_class():
    global _MODULES
    if _MODULES is None:
        _MODULES = _build_modules()
    return _MODULES[1]


def get_chunk_lm_class():
    """The decode-chunk twin (pool-free attention; shares the paged
    LM's parameter tree — see ChunkTransformerBlock)."""
    global _MODULES
    if _MODULES is None:
        _MODULES = _build_modules()
    return _MODULES[2]


def pool_is_flat(mesh=None) -> bool:
    """Whether KV pools store FLAT ``(L, pages, ps, d_model)`` — the r5
    default (the split (h, hd) trailing dims pad 2x under the TPU
    (8,128) tile).  The opt-in pallas kernels need the split layout
    (their BlockSpecs index it), but they are also force-disabled
    under a TP mesh — so a mesh stays flat regardless of the env
    opt-in.  ONE shared decision for every lane (PagedEngine and the
    speculative _PagedState must agree, or cross-lane bit-equality
    breaks on layout)."""
    if mesh is not None:
        return True
    return not paged_kernel_requested()


def kv_split(pool):
    """Split a pool argument into ``(pages, scales)`` — the r18 int8
    bundle is a 2-tuple ``(int8 pages, f32 per-page scales)``; a bare
    array (the native-dtype pool) splits to ``(pool, None)``.  Program
    functions call this at entry so ONE argument convention covers both
    pool dtypes (jit treats the tuple as a pytree; donating it donates
    both leaves)."""
    if isinstance(pool, tuple):
        return pool
    return pool, None


def kv_join(pages, scales):
    """Inverse of :func:`kv_split`."""
    if scales is None:
        return pages
    return (pages, scales)


def kv_scales_arg(sk, sv):
    """The ``kv_scales=`` argument for a split pool: ``None`` for a
    native pool, ``(sk, sv)`` for the int8 bundle.  ``sk is None`` is a
    pytree-STRUCTURE fact fixed at trace time, not a traced value — a
    helper so jitted callers don't spell a ternary the jit-purity
    linter cannot tell apart from tracer control flow."""
    if sk is None:
        return None
    return (sk, sv)


def write_kv(pk, pv, new_k, new_v, block_tables, start, valid, *, page_size, max_len,
             from_zero: bool = False):
    """Write (layers, B, L, h, hd) K/V into a paged pool.

    ``start``: (B,) absolute position of each row's first token;
    invalid lanes are redirected to trash page 0.  Shared by the
    continuous-batching engine and the speculative decoder.

    Lowering matters enormously on TPU: an arbitrary-index scatter
    serialises (measured ~0.22 ms per index row at d512 — it dominated
    both the decode chunk at 16 slots and the batched prefill at
    16x128 tokens), while ``dynamic_update_slice`` stays in place on
    scan carries and costs microseconds.  So every path here is DUS:

    * **decode steps (seg_len == 1)** — one DUS per slot.
    * **prefill (``from_zero=True``, static flag)** — writes always
      begin at position 0, so each (row, page) pair is one CONTIGUOUS
      page-block DUS; rows x pages unrolled statically.  Whole pages
      are written (pad positions land in the row's own page or, for
      rows without that page, in trash page 0 via the zero block-table
      entry) — attention masks by length, and later tokens overwrite.
    * **short segments (speculative verify)** — token-wise DUS,
      seg_len x rows unrolled.
    """
    import jax
    import jax.numpy as jnp

    # r18 int8 pool: the bundled ``(pages, scales)`` form takes the
    # quantising write path — pages are (re)quantised whole, one f32
    # scale per page per k/v kept exact in the sibling table
    pk_pages, sk = kv_split(pk)
    pv_pages, sv = kv_split(pv)
    if sk is not None:
        pk_pages, sk, pv_pages, sv = _write_kv_int8(
            pk_pages, sk, pv_pages, sv, new_k, new_v, block_tables, start,
            valid, page_size=page_size, max_len=max_len, from_zero=from_zero,
        )
        return (pk_pages, sk), (pv_pages, sv)

    # Two pool storage layouts (r5): FLAT ``(L, pages, ps, d_model)`` —
    # the default, because the split (heads=8, head_dim=64) trailing
    # dims pad 2x under the TPU (8,128) tile (measured: pool and ctx
    # buffers at 2.0x expansion in the HBM breakdown; a gather+attention
    # microbench ran 2.5x faster on the flat layout) — and the legacy
    # 5-d split layout, kept for the opt-in pallas kernels whose
    # BlockSpecs index (pages, ps, h, hd).  New K/V arrive split from
    # the module; merge the trailing dims to match a flat pool (h x hd
    # is contiguous, so the reshape is layout-preserving).
    if pk.ndim == 4 and new_k.ndim == 5:
        new_k = new_k.reshape(*new_k.shape[:3], -1)
        new_v = new_v.reshape(*new_v.shape[:3], -1)
    tail0 = (0,) * (pk.ndim - 3)

    seg_len = new_k.shape[2]
    B = new_k.shape[1]
    if seg_len == 1:
        pos = jnp.minimum(start, max_len - 1)  # (B,)
        page_idx = pos // page_size
        offs = pos % page_size
        for s in range(B):
            page = jnp.where(
                valid[s, 0], jnp.take(block_tables[s], page_idx[s]), 0
            )
            pk = jax.lax.dynamic_update_slice(
                pk, new_k[:, s][:, None], (0, page, offs[s]) + tail0
            )
            pv = jax.lax.dynamic_update_slice(
                pv, new_v[:, s][:, None], (0, page, offs[s]) + tail0
            )
        return pk, pv

    if from_zero:
        # rows x pages of contiguous block writes; pages a row never
        # allocated hold 0 in its block table -> the block lands in the
        # trash page, same redirection the scatter's valid-mask gave
        for s in range(B):
            for j in range(-(-seg_len // page_size)):
                lo = j * page_size
                blen = min(page_size, seg_len - lo)
                page = block_tables[s, j]
                pk = jax.lax.dynamic_update_slice(
                    pk, new_k[:, s, lo : lo + blen][:, None], (0, page, 0) + tail0
                )
                pv = jax.lax.dynamic_update_slice(
                    pv, new_v[:, s, lo : lo + blen][:, None], (0, page, 0) + tail0
                )
        return pk, pv

    # short mid-sequence segments (draft_k+1 wide): token-wise DUS
    pos = start[:, None] + jnp.arange(seg_len)[None, :]  # (B, L)
    pos = jnp.minimum(pos, max_len - 1)
    page_idx = pos // page_size
    offs = pos % page_size
    for s in range(B):
        for t in range(seg_len):
            page = jnp.where(
                valid[s, t], jnp.take(block_tables[s], page_idx[s, t]), 0
            )
            pk = jax.lax.dynamic_update_slice(
                pk, new_k[:, s, t][:, None, None], (0, page, offs[s, t]) + tail0
            )
            pv = jax.lax.dynamic_update_slice(
                pv, new_v[:, s, t][:, None, None], (0, page, offs[s, t]) + tail0
            )
    return pk, pv


def _write_kv_int8(pk, sk, pv, sv, new_k, new_v, block_tables, start, valid, *,
                   page_size, max_len, from_zero):
    """The quantising twin of :func:`write_kv` for the int8 pool.

    Same DUS lowering discipline and trash-page redirection as the
    native path, with one structural difference: int8 quantisation is a
    PAGE-granular property (one f32 scale per page per k/v), so every
    write touches whole pages —

    * **prefill (``from_zero``)** — each (row, page) block quantises
      fresh: per-layer abs-max over the block, scale = amax/127, pad
      positions zero (they contribute nothing to the abs-max, so a
      partial last page quantises at its live tokens' dynamic range).
    * **decode / speculative segments** — read-modify-write requant:
      dequantise the page at its old scale, ZERO the stale tail at or
      past the write offset (a recycled page's dead values must not
      inflate the new scale), insert the token, recompute the scale,
      requantise the whole page.  NUMERIC CAVEAT: a page filling token
      by token requantises up to ``page_size`` times, so earlier tokens'
      dequantised values can drift by ±scale/2 as the page's dynamic
      range grows — this is the int8 lane's documented regime
      (docs/architecture.md §5b), bounded by the top-1 agreement test.
    """
    import jax
    import jax.numpy as jnp

    if pk.ndim == 4 and new_k.ndim == 5:
        new_k = new_k.reshape(*new_k.shape[:3], -1)
        new_v = new_v.reshape(*new_v.shape[:3], -1)
    tail0 = (0,) * (pk.ndim - 3)
    tail_shape = pk.shape[3:]
    L = pk.shape[0]

    def _quant(pagef):
        # pagef: (L, 1, ps, *tail) f32 — one scale per LAYER (the page
        # axis is the sliced singleton)
        amax = jnp.max(jnp.abs(pagef), axis=tuple(range(1, pagef.ndim)))
        scale = jnp.maximum(amax / 127.0, 1e-8)  # (L,)
        q = jnp.clip(
            jnp.round(pagef / scale.reshape((L,) + (1,) * (pagef.ndim - 1))),
            -127, 127,
        ).astype(jnp.int8)
        return q, scale

    def _rmw_token(pool, scales, tok, page, off):
        # tok: (L, *tail) f32 — requant one page with ``tok`` at ``off``
        oldq = jax.lax.dynamic_slice(
            pool, (0, page, 0) + tail0, (L, 1, page_size) + tail_shape
        )
        olds = jax.lax.dynamic_slice(scales, (0, page), (L, 1))
        pagef = oldq.astype(jnp.float32) * olds.reshape(
            (L, 1, 1) + (1,) * len(tail_shape)
        )
        live = (jnp.arange(page_size) < off).reshape(
            (1, 1, page_size) + (1,) * len(tail_shape)
        )
        pagef = jnp.where(live, pagef, 0.0)
        pagef = jax.lax.dynamic_update_slice(
            pagef, tok[:, None, None], (0, 0, off) + tail0
        )
        q, scale = _quant(pagef)
        pool = jax.lax.dynamic_update_slice(pool, q, (0, page, 0) + tail0)
        scales = jax.lax.dynamic_update_slice(
            scales, scale[:, None], (0, page)
        )
        return pool, scales

    seg_len = new_k.shape[2]
    B = new_k.shape[1]
    new_kf = new_k.astype(jnp.float32)
    new_vf = new_v.astype(jnp.float32)

    if from_zero:
        for s in range(B):
            for j in range(-(-seg_len // page_size)):
                lo = j * page_size
                blen = min(page_size, seg_len - lo)
                page = block_tables[s, j]
                for pool_name, pool, scales, new in (
                    ("k", pk, sk, new_kf), ("v", pv, sv, new_vf)
                ):
                    blk = new[:, s, lo:lo + blen][:, None]  # (L,1,blen,*)
                    if blen < page_size:
                        pad = [(0, 0)] * blk.ndim
                        pad[2] = (0, page_size - blen)
                        blk = jnp.pad(blk, pad)
                    q, scale = _quant(blk)
                    pool = jax.lax.dynamic_update_slice(
                        pool, q, (0, page, 0) + tail0
                    )
                    scales = jax.lax.dynamic_update_slice(
                        scales, scale[:, None], (0, page)
                    )
                    if pool_name == "k":
                        pk, sk = pool, scales
                    else:
                        pv, sv = pool, scales
        return pk, sk, pv, sv

    if seg_len == 1:
        pos = jnp.minimum(start, max_len - 1)  # (B,)
        page_idx = pos // page_size
        offs = pos % page_size
        for s in range(B):
            page = jnp.where(
                valid[s, 0], jnp.take(block_tables[s], page_idx[s]), 0
            )
            pk, sk = _rmw_token(pk, sk, new_kf[:, s, 0], page, offs[s])
            pv, sv = _rmw_token(pv, sv, new_vf[:, s, 0], page, offs[s])
        return pk, sk, pv, sv

    # short mid-sequence segments (speculative verify): token-wise RMW
    pos = start[:, None] + jnp.arange(seg_len)[None, :]  # (B, L)
    pos = jnp.minimum(pos, max_len - 1)
    page_idx = pos // page_size
    offs = pos % page_size
    for s in range(B):
        for t in range(seg_len):
            page = jnp.where(
                valid[s, t], jnp.take(block_tables[s], page_idx[s, t]), 0
            )
            pk, sk = _rmw_token(pk, sk, new_kf[:, s, t], page, offs[s, t])
            pv, sv = _rmw_token(pv, sv, new_vf[:, s, t], page, offs[s, t])
    return pk, sk, pv, sv


def paged_hbm_accounting(
    *,
    streams: int,
    ctx_len: int,
    d_model: int,
    num_layers: int,
    page_size: int = 64,
    steps_per_call: int = 8,
    dtype_bytes: int = 2,
    flat_pool: bool = True,
    chunk_impl: str = "ring",
    donated: bool = True,
    split_tile_pad: float = 2.0,
    cached_prefix_pages: int = 0,
    tp_degree: int = 1,
    dp_degree: int = 1,
    num_pool_pages: Optional[int] = None,
    num_heads: Optional[int] = None,
    inflight_prefill_tokens: int = 0,
    adapter_bytes: int = 0,
    reclaimable_weight_bytes: int = 0,
    kv_dtype: str = "bf16",
    host_tier_gib: float = 0.0,
) -> Dict[str, int]:
    """Pool-HBM bytes for ``streams`` concurrent streams at ``ctx_len``
    tokens — the capacity model the bench certifies (VERDICT r5 #3/#5).

    Terms, each measured in earlier rounds rather than assumed:

    * **pool (at rest)** — pages x page_size x d_model x 2 (K+V) x
      layers.  The flat layout stores logical bytes; the split
      (heads, head_dim) layout physically pads ``split_tile_pad``
      (2.0x measured under the TPU (8,128) tile — §10b r5b).
    * **donated vs copied** — the chunk program donates pk/pv
      (``donate_argnums``), so exactly ONE pool copy is live during a
      chunk; without donation XLA keeps input AND output pools and the
      at-rest term doubles.  ``donated=False`` prices that world — the
      accounting the capacity claim must state.
    * **working set (ring impl only)** — the once-per-chunk ctx copy
      (split in flight: pays the tile pad) plus the step-indexed ring;
      the pool impl reads the pool per step and carries no copy.
      Under the r6 length-bucketed gather this is the WORST case
      (uniform ctx_len); mixed traffic gathers less.

    * **cached prefix pages (r9)** — LRU-parked prefix-cache pages are
      RECLAIMABLE: allocation evicts them on demand, so they never
      reduce admissible capacity.  ``cached_prefix_pages`` prices the
      bytes they occupy *between* reclaims (``reclaimable_bytes``)
      without adding to ``peak_bytes`` — the accounting the admission
      guard and ``paged_capacity_streams`` rely on.

    * **tensor parallelism (r11)** — ``tp_degree > 1`` prices the
      PER-SHARD bytes one device holds: the pool and the in-flight
      working set are sharded over heads on the ``model`` axis, so
      every KV term divides by the degree (tables/lengths replicate
      but are KBs against the pool's GBs and stay out of scope like
      the host runtime).  Capacity under a fixed per-chip budget
      therefore SCALES with the degree — the accounting
      ``paged_capacity_streams`` certifies.  Pass ``num_heads`` to
      carry the head-sharding constraint: an indivisible head count
      leaves the pool REPLICATED at engine load
      (``shard_decode_state``'s WARN fallback), so the accounting
      prices FULL bytes rather than certifying capacity the fallback
      cannot deliver.

    * **in-flight prefill scratch (r15)** — under chunked prefill a
      stream admitted but still chunking holds ALL its prompt pages
      mapped (admission allocates the whole prompt's block table up
      front; slices fill it over several waves) while contributing no
      decode.  ``inflight_prefill_tokens`` prices those mapped pages
      (``inflight_prefill_bytes``, included in ``peak_bytes``) so
      :func:`paged_capacity_streams` cannot over-admit during the
      chunking window — the over-admission bug the r15 satellite
      fixed.

    * **adapter pool (r16)** — multi-LoRA serving preallocates a
      slot-granular factor pool next to the KV pool
      (``LoraPool.hbm_bytes`` — already per-shard under TP, since each
      target's sharded factor follows its base layer's megatron
      sharding).  ``adapter_bytes`` prices it into ``peak_bytes``: the
      pool is resident whether or not slots are full, so capacity
      planning must reserve it off the top like in-flight prefill.
      ``reclaimable_weight_bytes`` prices the weight registry's CACHED
      (refcount-0) sets next to the prefix cache's reclaimable pages —
      capacity, never cost.

    * **data axis / sequence sharding (r19)** — ``dp_degree > 1``
      prices the 2-D serving mesh: the pool's PAGE dim is sharded over
      ``data`` (on top of the ``model`` heads sharding), so per-device
      pool bytes divide by BOTH degrees — this is the long-context
      claim: a 32k stream whose full pool bytes exceed one chip's
      budget admits when its per-shard slice fits
      (:func:`paged_max_context` inverts this).  Pass
      ``num_pool_pages`` (the engine's dp-rounded pool) to carry the
      page-divisibility constraint: an indivisible pool leaves the
      page dim REPLICATED at engine load (``shard_decode_state``'s
      WARN fallback), so the accounting prices full page bytes rather
      than certifying capacity the fallback cannot deliver.  The ring
      working set divides with the lane sharding (slot-major arrays
      batch-shard over ``data``); tables/lengths stay out of scope as
      under TP.

    * **int8 KV pool (r18)** — ``kv_dtype="int8"`` prices pages at ONE
      byte per element plus the sibling scale table's 8 bytes per page
      (one f32 per page per k/v per layer): ~2x
      ``paged_capacity_streams`` at equal budget vs bf16.  In-flight
      prefill scratch and reclaimable prefix pages are pool pages, so
      they reprice the same way; the ring working set does NOT — the
      gathered ctx/ring copies hold the engine's compute dtype (and the
      int8 pool is pool-impl-only regardless).

    * **host KV tier (r22)** — ``host_tier_gib`` prices the
      ``SELDON_TPU_KV_OFFLOAD`` host-RAM container budget as its own
      section: ``host_tier_bytes`` is HOST memory (never added to
      ``peak_bytes`` — the tier exists so HBM can shed), and the whole
      budget is ``host_reclaimable_bytes`` because every entry is a
      re-derivable cache the OS may reclaim by dropping demoted pages
      (they re-prefill on miss, exactly as without the tier).

    BASE weights, activations, and the host runtime stay out of scope:
    this prices what scales with streams and adapter multiplexing.
    """
    shard = max(1, int(tp_degree))
    if num_heads is not None and num_heads % shard:
        # mirror shard_decode_state: this configuration serves with a
        # replicated pool, so one device really holds the full bytes
        shard = 1
    dshard = max(1, int(dp_degree))
    if num_pool_pages is not None and num_pool_pages % dshard:
        # mirror shard_decode_state's page-dim guard: an indivisible
        # pool replicates over `data`, so price the full page bytes
        dshard = 1
    kv_shard = shard * dshard
    pages = -(-ctx_len // page_size)
    kv_int8 = kv_dtype == "int8"
    pool_elt_bytes = 1 if kv_int8 else dtype_bytes
    tok_bytes = num_layers * d_model * 2 * pool_elt_bytes
    # sibling scale table: one f32 per page per k/v per layer
    page_scale_bytes = num_layers * 2 * 4 if kv_int8 else 0
    pool_pad = 1.0 if flat_pool else split_tile_pad
    page_bytes = page_size * tok_bytes * pool_pad + page_scale_bytes
    pool = int(streams * pages * page_bytes) // kv_shard
    ws = 0
    if chunk_impl == "ring":
        # the ring impl's gathered working set holds the COMPUTE dtype
        ws = int(
            streams * (pages * page_size + steps_per_call)
            * num_layers * d_model * 2 * dtype_bytes * split_tile_pad
        ) // kv_shard
    at_rest = pool if donated else 2 * pool
    inflight_pages = -(-int(inflight_prefill_tokens) // page_size)
    inflight = int(inflight_pages * page_bytes) // kv_shard
    return {
        "pool_bytes": pool,
        "working_set_bytes": ws,
        "peak_bytes": at_rest + ws + inflight + int(adapter_bytes),
        "per_stream_bytes": (at_rest + ws) // max(1, streams),
        "reclaimable_bytes": int(
            cached_prefix_pages * page_bytes
        ) // kv_shard + int(reclaimable_weight_bytes),
        "inflight_prefill_bytes": inflight,
        "adapter_bytes": int(adapter_bytes),
        "reclaimable_weight_bytes": int(reclaimable_weight_bytes),
        "tp_degree": shard,
        "dp_degree": dshard,
        # host KV tier (r22): HOST bytes, never HBM — always present
        # (0 when the tier is off) so capacity dashboards need no
        # key-existence branch
        "host_tier_bytes": int(float(host_tier_gib) * (1 << 30)),
        "host_reclaimable_bytes": int(float(host_tier_gib) * (1 << 30)),
    }


def paged_capacity_streams(
    budget_bytes: int, ctx_len: int, *, donated: bool = True,
    inflight_prefill_tokens: int = 0, adapter_bytes: int = 0, **model_kw
) -> int:
    """Max concurrent streams whose paged KV peak fits ``budget_bytes``
    at ``ctx_len`` tokens each (per-stream cost is linear in streams,
    so this is one division over the single-stream accounting).

    Prefix-cache residue never prices into this: LRU-cached pages are
    reclaimable on demand (``cached_prefix_pages`` above contributes
    ``reclaimable_bytes``, not ``peak_bytes``), so a warm cache holds
    the same number of admissible streams as a cold pool.

    In-flight prefill scratch DOES price into this (r15 bugfix):
    ``inflight_prefill_tokens`` — prompt tokens of streams admitted
    but still chunking their prefill — reserves its mapped pages off
    the top of the budget BEFORE the per-stream division, because
    those pages are neither free nor reclaimable while the slices run.
    Without the term, chunked prefill let the planner admit streams
    whose pages the chunking prompts already held.

    The multi-LoRA adapter pool (r16) reserves off the top the same
    way: ``adapter_bytes`` (per-shard, ``LoraPool.hbm_bytes``) is
    resident regardless of stream count, so it must come out of the
    budget BEFORE the per-stream division — otherwise enabling
    adapters would silently certify KV capacity the factor pool
    already occupies."""
    one = paged_hbm_accounting(
        streams=1, ctx_len=ctx_len, donated=donated,
        inflight_prefill_tokens=inflight_prefill_tokens,
        adapter_bytes=adapter_bytes, **model_kw
    )
    fixed = one["inflight_prefill_bytes"] + one["adapter_bytes"]
    per_stream = max(1, one["peak_bytes"] - fixed)
    usable = max(0, int(budget_bytes) - fixed)
    return int(usable // per_stream)


def paged_max_context(
    budget_bytes: int, *, page_size: int = 64, max_len_cap: int = 1 << 20,
    **model_kw,
) -> int:
    """Largest page-aligned context ONE stream can hold under a
    per-chip HBM budget — :func:`paged_capacity_streams` inverted over
    ``ctx_len`` instead of ``streams`` (the ``longctx_max_len`` bench
    key).  Per-stream peak bytes grow monotonically with context, so a
    binary search over page counts suffices; ``dp_degree > 1`` in
    ``model_kw`` is the whole point — sequence sharding divides the
    per-shard bytes, so the admissible context multiplies with the
    data axis (the 2-D mesh's long-context claim, priced not assumed).
    Returns 0 when not even one page fits."""
    def fits(ctx_len: int) -> bool:
        one = paged_hbm_accounting(
            streams=1, ctx_len=ctx_len, page_size=page_size, **model_kw
        )
        return one["peak_bytes"] <= int(budget_bytes)

    lo, hi = 0, max_len_cap // page_size
    if not fits(page_size):
        return 0
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if fits(mid * page_size):
            lo = mid
        else:
            hi = mid - 1
    return lo * page_size


# ---------------------------------------------------------------------------
# host-side engine
# ---------------------------------------------------------------------------


# Chain root for the prefix index: page i's key is
# ``prefix_chain_key(key_{i-1}, page_tokens)`` with key_0 chained off
# this constant, so one key identifies the ENTIRE token prefix up to
# and including its page (vLLM's hash-chained block keying).  Lookup
# walks root -> leaf and stops at the first miss, which is what makes
# an evicted interior page safely sever its (now unreachable)
# descendants instead of corrupting them.
_PREFIX_ROOT = 0x9E3779B97F4A7C15


def prefix_chain_key(parent: int, tokens: Tuple[int, ...]) -> int:
    """Key of the prefix ending at a full page: ``parent`` is the key of
    the preceding page (``_PREFIX_ROOT`` for page 0), ``tokens`` the
    page's token ids.  Module-level so tests can monkeypatch it into a
    colliding hash — entries verify token equality before sharing, so a
    collision must degrade to a private prefill, never to cross-stream
    KV contamination."""
    return hash((parent, tokens))


class _CachedPrefix:
    """One registered full prompt page in the prefix index.

    The page's KV bytes are a pure function of the token chain the key
    encodes (greedy prefill is deterministic), which is why any stream
    whose prompt starts with that chain can map the page read-only."""

    __slots__ = ("key", "page", "tokens", "parent")

    def __init__(self, key: int, page: int, tokens: Tuple[int, ...], parent: int):
        self.key = key
        self.page = page
        self.tokens = tokens
        self.parent = parent


# SLO lifecycle counters threaded engine_stats -> flight-recorder chunk
# records (per-wave deltas) -> GenerationPrometheusBridge -> dashboards
_SLO_COUNTER_KEYS = ("shed", "expired", "preempted", "restored",
                     "drained", "replayed", "quarantined")

# hierarchical KV tier (r22): the counter keys engine_stats sheds when
# SELDON_TPU_KV_OFFLOAD=0, and the per-wave delta subset the flight
# recorder's chunk records carry when the tier is on
_TIER_COUNTER_KEYS = (
    "kv_tier_demotions", "kv_tier_promotions", "kv_tier_host_hits",
    "kv_tier_disk_hits", "kv_tier_misses", "kv_tier_evictions",
    "kv_tier_bytes_demoted", "kv_tier_bytes_promoted",
)
_TIER_DELTA_KEYS = ("kv_tier_demotions", "kv_tier_promotions",
                    "kv_tier_host_hits", "kv_tier_disk_hits")


class _Stream:
    """One in-flight generation request bound to a slot."""

    __slots__ = (
        "req_id", "prompt", "max_new", "temperature", "top_k", "eos_id",
        "seed", "tokens", "event", "result", "error", "slot", "pages",
        "pending", "draft_hint", "token_queue", "streamed", "cancelled",
        "trace_id", "parent_span_id", "puid", "t_submit",
        "t_prefill_start", "t_decode_start", "t_first_token", "t_finish",
        "queue_depth_at_submit", "cached_len", "prefilled", "priority",
        "deadline", "preempted", "kv_export", "kv_import", "kv_payload",
        "kv_imported", "adapter", "adapter_slot", "adapter_pinned",
        "cost_page_s", "cost_t", "cost_prefill_tokens",
        "cost_decode_tokens", "cost_preempts", "cost_restores",
        "cost_closed", "tier_promote",
    )

    def __init__(self, req_id, prompt, max_new, temperature, top_k, eos_id, seed):
        self.req_id = req_id
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.seed = seed
        self.tokens: List[int] = []
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        self.slot: Optional[int] = None
        self.pages: List[int] = []
        # tokens already resident in shared prefix-cache pages at
        # admission (page-aligned); prefill runs only past this point
        self.cached_len = 0
        # prompt tokens whose KV is ACTUALLY in the pool: cached_len at
        # admission, advanced by every prefill slice (monolithic
        # prefill jumps straight to len(prompt)); a stream decodes only
        # once prefilled == len(prompt) — the chunked-prefill state
        self.prefilled = 0
        # disaggregation (r15): kv_export streams finish at the end of
        # prefill with their pages read back into kv_payload instead of
        # decoding; kv_import carries a prefill worker's payload whose
        # pages are scatter-written at admission (no prefill FLOPs)
        self.kv_export = False
        self.kv_import: Optional[Dict[str, Any]] = None
        self.kv_payload: Optional[Dict[str, Any]] = None
        # the import payload was consumed (pages scatter-written): the
        # stream now decodes like a local one, but drain still treats
        # it as a disaggregation stream (the r15 journal exclusion)
        self.kv_imported = False
        # speculative mode: the next greedy token (argmax of the last
        # verified logits), decided on host between verify rounds
        self.pending: Optional[int] = None
        # draft='oracle' benchmarking lane: the expected continuation
        self.draft_hint: Optional[np.ndarray] = None
        # token streaming: when set, every decode chunk pushes its new
        # tokens here as they land; None marks the end of the stream.
        # `streamed` is the already-pushed cursor — eviction resets
        # tokens but not the cursor, so the deterministic re-run
        # resumes pushing exactly where the consumer left off
        self.token_queue: Optional["_queue.Queue"] = None
        self.streamed = 0
        self.cancelled = False
        # lifecycle-trace linkage (set by submit()): the request puid and
        # the submitter's span — gen.* spans emitted from the decode-loop
        # thread link by these explicitly (contextvars don't cross
        # threads).  Zeros/None when tracing is off: no per-stream cost.
        self.trace_id = ""
        self.parent_span_id: Optional[str] = None
        # request identity for forensics joins (r21): the ingress puid
        # when the submitter carries one (tracing NOT required), else
        # the trace id — flight-recorder wave records and capture
        # containers key on it
        self.puid = ""
        self.t_submit = 0.0
        # wall time the stream's FIRST prefill slice started: with
        # t_submit/t_decode_start/t_first_token this decomposes a
        # request's latency into queue-wait / prefill / decode without
        # a tracer (the bench's p99-terms source)
        self.t_prefill_start = 0.0
        self.t_decode_start = 0.0
        # wall time the stream's FIRST decode token landed (the TTFT
        # numerator: t_first_token - t_submit); always stamped — the
        # bench's interactive-TTFT gate and the profile tool's TTFT
        # column must not require a tracer
        self.t_first_token = 0.0
        # wall time the result was delivered (_finish_locked): closes
        # the queue_wait / prefill / decode request decomposition
        self.t_finish = 0.0
        self.queue_depth_at_submit = 0
        # SLO lifecycle (r10): admission/shedding order (higher wins),
        # absolute time.monotonic() expiry (None = no deadline), and
        # whether this stream was preemptively evicted (its eventual
        # re-admission counts as a restore)
        self.priority = 0
        self.deadline: Optional[float] = None
        self.preempted = False
        # multi-LoRA (r16): the named adapter this stream decodes with
        # (None = base model), its slot in the engine's factor pool
        # (0 = the zero adapter), and whether the stream still holds a
        # pin on that slot (released exactly once at termination)
        self.adapter: Optional[str] = None
        self.adapter_slot = 0
        self.adapter_pinned = False
        # per-request cost ledger (r20): KV page-seconds accrued so far
        # (the occupancy integral), the monotonic stamp of the last
        # accrual (0.0 = not holding pages), prefill/decode tokens this
        # stream's device work actually computed (re-derivation after
        # eviction re-accrues — it is cost), preempt/restore counts,
        # and the close guard (totals accrue into the engine EXACTLY
        # once per stream)
        self.cost_page_s = 0.0
        self.cost_t = 0.0
        self.cost_prefill_tokens = 0
        self.cost_decode_tokens = 0
        self.cost_preempts = 0
        self.cost_restores = 0
        self.cost_closed = False
        # hierarchical KV tier (r22): admission's chain walk hit the
        # host/disk tier — {"pages": fresh HBM pages, "entries":
        # popped tier entries}; consumed by _tier_promote_ready's
        # donated scatter before the stream's first device work, put
        # back into the tier if the stream dies before that
        self.tier_promote: Optional[Dict[str, Any]] = None


def journal_entry(
    *,
    req_id: Any,
    prompt: List[int],
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_id: int = -1,
    seed: int = 0,
    priority: int = 0,
    deadline_remaining_ms: Optional[float] = None,
    streamed: int = 0,
    stream_tokens: bool = False,
    tokens_decoded: int = 0,
    adapter: Optional[str] = None,
) -> Dict[str, Any]:
    """THE drain-journal entry schema — the one key set
    :meth:`PagedEngine.replay` consumes.  Both builders go through
    here (``PagedEngine._journal_entry`` from a live stream object,
    ``models/disagg.migration_journal_entry`` from a migration
    payload), so a field added to the recipe cannot drift between the
    drain lane and the migration-fallback lane."""
    return {
        "req_id": req_id,
        "prompt": prompt,
        "max_new_tokens": int(max_new_tokens),
        "temperature": float(temperature),
        "top_k": int(top_k),
        "eos_id": int(eos_id),
        "seed": int(seed),
        "priority": int(priority),
        "deadline_remaining_ms": deadline_remaining_ms,
        "streamed": int(streamed),
        "stream_tokens": bool(stream_tokens),
        "tokens_decoded": int(tokens_decoded),
        "adapter": adapter,
    }


class PagedEngine:
    """Continuous-batching decode engine over a paged K/V pool.

    ``submit()`` from any thread; ``step()`` (or the background loop in
    :class:`StreamingLM`) advances every active stream by up to
    ``steps_per_call`` tokens in one compiled program.

    One decode program total is compiled (shapes are fixed by
    ``max_slots``/``steps_per_call``), plus one prefill program per
    prompt bucket — the same "no request pays a trace" invariant the
    jaxserver bucket ladder enforces.
    """

    def __init__(
        self,
        params,
        *,
        vocab_size: int,
        d_model: int = 256,
        num_layers: int = 4,
        num_heads: int = 8,
        max_len: int = 2048,
        page_size: int = 64,
        num_pages: Optional[int] = None,
        max_slots: int = 8,
        steps_per_call: int = 8,
        max_steps_per_call: int = 0,
        prompt_buckets: Optional[Sequence[int]] = None,
        dtype: Any = None,
        mesh: Any = None,
        tp: Optional[int] = None,
        dp: Optional[int] = None,
        model_axis: str = "model",
        data_axis: str = "data",
        shard_min_weight_size: int = 16_384,
        quantize: str = "",
        precision: str = "",
        speculative: Optional[Dict[str, Any]] = None,
        prefix_cache: Optional[bool] = None,
        max_queue: int = 0,
        chunk_token_budget: int = 0,
        max_adapters: int = 0,
        lora_rank: int = 8,
        weight_registry: Any = None,
    ):
        import jax
        import jax.numpy as jnp

        if max_len % page_size:
            raise ValueError(f"max_len {max_len} must be a multiple of page_size {page_size}")
        # serving-mesh knobs (r11 tp, r19 dp): an explicit mesh wins;
        # otherwise `tp=`/`dp=` (constructor) / SELDON_TPU_TP /
        # SELDON_TPU_DP (env) resolve through the ONE precedence home
        # (parallel.mesh.resolve_mesh) into the {"data": dp, "model":
        # tp} serving mesh — size-1 axes dropped, so dp=1 keeps the
        # PR 7 1-D mesh (and dp=tp=1 keeps mesh=None) byte-identical —
        # degrading shrink-data-first with a WARN when the host exposes
        # fewer devices: one deployment config rolls out across pod and
        # dev hosts unchanged
        if mesh is None:
            from seldon_core_tpu.parallel.mesh import resolve_mesh

            mesh = resolve_mesh(
                tp=tp, dp=dp, model_axis=model_axis, data_axis=data_axis
            )
        from seldon_core_tpu.ops.surgery import (
            quantize_mode_for,
            validate_precision,
            validate_quantize_mode,
        )

        validate_quantize_mode(quantize)
        # precision="w8a8": every decode projection runs int8×int8 with
        # int32 accumulation (ops/w8a8.py, dynamic per-tensor activation
        # scales) on top of the at-rest surgery; "int8w" is the
        # weight-only lane under its serving-config name
        self.precision = validate_precision(precision) or "bf16"
        quantize = quantize or quantize_mode_for(self.precision)
        if quantize == "int8":
            # weight-only int8: weights rest in HBM at half the bytes
            # and dequantise once per chunk program (measured 1.38x
            # decode rate; per-step dequant measured 0.48x — it does
            # not fuse).  Composes with tensor-parallel: the spec
            # inference treats each QuantizedKernel as one unit — q
            # sharded on its output-channel dim with scale sharded the
            # same axis (or scale replicated when q shards an input
            # dim), so the fused dequant needs no resharding collective
            from seldon_core_tpu.ops.surgery import quantize_params

            params, self.quantize_manifest = quantize_params(params)
        else:
            self.quantize_manifest = []
        self.quantize = quantize
        self._jax, self._jnp = jax, jnp
        dtype = dtype or jnp.bfloat16
        self._dtype = dtype
        self.vocab_size = int(vocab_size)
        self.max_len = int(max_len)
        self.page_size = int(page_size)
        self.pages_per_stream = self.max_len // self.page_size
        self.max_slots = int(max_slots)
        self.steps_per_call = int(steps_per_call)
        # saturated-decode ladder: when no stream is waiting for a slot,
        # chunks grow (x2 up to max_steps_per_call) so one program call
        # decodes more tokens — admission latency only pays the SHORT
        # chunk, because a non-empty queue pins chunks at steps_per_call.
        # Each ladder size is one compiled program (power-of-two ladder
        # keeps the count logarithmic).
        self.max_steps = max(self.steps_per_call, int(max_steps_per_call))
        # default pool = worst case (every slot full-length) + trash page;
        # shrink for the actual memory win when streams are short-lived
        self.num_pages = int(
            num_pages or self.max_slots * self.pages_per_stream + 1
        )
        # data-axis degree this engine will run at (r19) — resolved
        # here because the pool geometry below depends on it
        if mesh is not None:
            from seldon_core_tpu.parallel.mesh import mesh_shape as _msh

            _dp = int(_msh(mesh).get(data_axis, 1))
        else:
            _dp = 1
        # sequence sharding (r19): the data axis also shards the pool's
        # PAGE dim, so one long stream's KV pages spread across the
        # axis (per-shard residency = pool/dp — the long-context
        # capacity claim paged_hbm_accounting(dp_degree=) prices).
        # SELDON_TPU_SEQ_SHARD=0 keeps the pool replicated over data:
        # pure throughput replica groups, no capacity claim.
        self._seq_shard = _knobs.flag("SELDON_TPU_SEQ_SHARD")
        if _dp > 1 and self._seq_shard and self.num_pages % _dp:
            # page-dim sharding needs equal shards; rounding the pool
            # UP never shrinks capacity and only fires under dp>1, so
            # dp=1 pool geometry stays byte-identical
            self.num_pages += -self.num_pages % _dp
        self.prompt_buckets = sorted(set(prompt_buckets or _buckets_for(max_len)))
        head_dim = d_model // num_heads
        module_precision = "w8a8" if self.precision == "w8a8" else "bf16"
        self.module = get_paged_lm_class()(
            vocab_size=vocab_size, d_model=d_model, num_layers=num_layers,
            num_heads=num_heads, max_len=max_len, dtype=dtype,
            precision=module_precision,
            # pallas decode kernel and heads-sharded pools don't mix:
            # GSPMD can't partition the custom call, so a TP mesh would
            # all-gather the pool per layer per step
            decode_kernel=mesh is None,
        )
        # decode-chunk twin: pool-free attention over a once-per-chunk
        # gathered context + in-chunk ring (same parameter tree — the
        # r5 fix for per-step gather cost scaling superlinearly with
        # slots).  SELDON_TPU_CHUNK_IMPL=pool restores the legacy
        # per-step-gather chunk for A/B.
        import os as _os

        self.chunk_module = get_chunk_lm_class()(
            vocab_size=vocab_size, d_model=d_model, num_layers=num_layers,
            num_heads=num_heads, max_len=max_len, dtype=dtype,
            precision=module_precision,
        )
        # COUPLED ENV KNOBS: SELDON_TPU_PAGED_KERNEL opts into the
        # pallas decode kernels, but those live in the POOL chunk's
        # per-step attention — the default ring chunk never reads the
        # pool per step, so with CHUNK_IMPL=ring the kernel opt-in
        # would only buy the split-layout pool's 2x HBM padding
        # (pool_is_flat keys on the kernel env) with ZERO speed effect.
        # Unset CHUNK_IMPL therefore auto-selects the pool impl when
        # the kernel opt-in can actually fire — same eligibility terms
        # as the block's gate (bf16, no TP mesh, TPU backend unless
        # forced); a requested-but-ineligible kernel keeps the ring
        # chunk and says why.  An EXPLICIT ring choice wins but is
        # warned about.
        kernel_mode = paged_kernel_mode()
        kernel_eligible = paged_kernel_static_eligible(
            kernel_mode, mesh is None, dtype
        )
        self._chunk_impl = _knobs.raw("SELDON_TPU_CHUNK_IMPL", "")
        if not self._chunk_impl:
            self._chunk_impl = "pool" if kernel_eligible else "ring"
            if kernel_eligible:
                logger.info(
                    "SELDON_TPU_PAGED_KERNEL is set: auto-selecting the pool "
                    "chunk impl (the pallas decode kernel lives in its "
                    "per-step attention; the ring chunk never reaches it)"
                )
            elif paged_kernel_explicit(kernel_mode):
                # the "auto" default resolving to the gather lane is
                # silent by design (r18) — only an EXPLICIT "1"/"force"
                # that cannot fire deserves the WARN
                logger.warning(
                    "SELDON_TPU_PAGED_KERNEL=%s requested but the kernel "
                    "cannot run here (needs bf16/f32, no TP mesh, and a TPU "
                    "backend unless force) — keeping the ring chunk; note "
                    "the env still selects the split pool layout",
                    kernel_mode,
                )
        elif paged_kernel_explicit(kernel_mode) and self._chunk_impl == "ring":
            logger.warning(
                "SELDON_TPU_PAGED_KERNEL is set but SELDON_TPU_CHUNK_IMPL="
                "ring: the ring chunk never invokes the pallas decode "
                "kernel, so this combination pays the split-layout pool's "
                "2x HBM padding with no speed effect — set "
                "SELDON_TPU_CHUNK_IMPL=pool to actually exercise the kernel"
            )
        # r6 length-bucketed context gather: inside ONE chunk program,
        # lanes are permuted bucket-sorted (shortest contexts first) and
        # split into 2 static buckets, each gathering/attending at its
        # own power-of-two page horizon — mixed-length traffic stops
        # paying the longest stream's context cost on every step, with
        # no extra dispatch (the constraint that killed per-group
        # CALLS).  "1" disables (the A/B + parity knob); uniform
        # traffic degenerates to one bucket automatically (identical
        # horizons), so the uniform-load programs are byte-identical
        # with the knob on.
        buckets_env = _knobs.raw("SELDON_TPU_CTX_BUCKETS", "") or "2"
        if buckets_env not in ("1", "2"):
            raise ValueError(
                f"SELDON_TPU_CTX_BUCKETS={buckets_env!r}: supported values "
                "are '1' (disable) and '2' (default)"
            )
        self._ctx_buckets = int(buckets_env)
        # pool storage layout (r5): FLAT (L, pages, ps, d_model) by
        # default — the split (h=8, hd=64) trailing dims pad 2x under
        # the TPU (8,128) tile (pool AND gathered-ctx buffers at 2.0x
        # in the HBM breakdown).  Shared decision helper: kernel mode
        # keeps split, a TP mesh is always flat (kernels can't run
        # there anyway)
        self._pool_flat = pool_is_flat(mesh)
        pool_shape = (
            (num_layers, self.num_pages, self.page_size, d_model)
            if self._pool_flat
            else (num_layers, self.num_pages, self.page_size, num_heads, head_dim)
        )
        # r18: which decode lane this replica actually runs — the
        # kernel fires only when the pool chunk invokes it against a
        # split pool; exported as the `kernel_active` gauge so
        # dashboards see the lane, not just a one-shot WARN
        self._kernel_active = bool(
            self._chunk_impl == "pool" and kernel_eligible
            and not self._pool_flat
        )
        # r18 int8 KV pool: pages rest int8 with ONE f32 scale per page
        # per k/v in a sibling (layers, num_pages) table — half the
        # pool bytes (≈2x paged_capacity_streams), dequantised
        # in-register by the decode kernel and right after the fetch by
        # the gather lane.  Single-chip pool-impl only: the ring chunk
        # never rereads the pool per step (its ctx gather would need a
        # third dequant site), and GSPMD sharding of the scale table is
        # not priced — both degrade to the native pool with a WARN.
        kv_dtype = paged_kv_dtype_mode()
        self._kv_int8 = False
        if kv_dtype == "int8":
            if mesh is not None or self._chunk_impl != "pool":
                logger.warning(
                    "SELDON_TPU_KV_DTYPE=int8 requested but the int8 KV "
                    "pool is single-chip pool-impl only (mesh=%s, "
                    "chunk_impl=%s) — keeping the native pool dtype",
                    mesh is not None, self._chunk_impl,
                )
            else:
                self._kv_int8 = True
        elif kv_dtype not in ("bf16", ""):
            raise ValueError(
                f"SELDON_TPU_KV_DTYPE={kv_dtype!r}: supported values are "
                "'bf16' (native pool dtype) and 'int8'"
            )
        pool_dtype = jnp.int8 if self._kv_int8 else dtype
        self._pool_dtype = pool_dtype
        # tensor-parallel decode: megatron-style param shardings + the
        # pool sharded on its heads axis (dim 3 either way — in the
        # flat layout d_model is head-major contiguous, so sharding it
        # at head boundaries is the same partition; created sharded,
        # never materialised on one device); XLA inserts the ICI
        # collectives inside the SAME compiled chunk program (the
        # scaling-book recipe — no hand-written collectives).
        # mesh=None -> plain pools
        from seldon_core_tpu.parallel.sharding import shard_decode_state

        self.params, self.pages_k, self.pages_v = shard_decode_state(
            params, mesh, pool_shape=pool_shape, dtype=pool_dtype,
            model_axis=model_axis, data_axis=data_axis,
            min_weight_size=shard_min_weight_size,
            num_heads=num_heads, seq_shard=self._seq_shard,
        )
        # sibling per-page scale tables (int8 pool only): one f32 per
        # page per k/v, indexed exactly like the pool's page axis — the
        # export/migration/import paths slice them with the same page
        # index lists the pages use
        if self._kv_int8:
            self.scales_k = jnp.zeros((num_layers, self.num_pages), jnp.float32)
            self.scales_v = jnp.zeros((num_layers, self.num_pages), jnp.float32)
        else:
            self.scales_k = self.scales_v = None
        # TP bookkeeping: the degree this engine actually runs at and
        # the PER-SHARD bytes one device holds for the K+V pool (the
        # number HBM planning cares about — the global pool is sliced
        # over heads, so per-device residency shrinks with the degree;
        # an unshardable pool reports full bytes honestly)
        self._mesh = mesh
        self._model_axis = model_axis
        self._data_axis = data_axis
        self.dp_degree = _dp
        if mesh is not None:
            from seldon_core_tpu.parallel.mesh import mesh_shape

            self.tp_degree = int(mesh_shape(mesh).get(model_axis, 1))
            shard = self.pages_k.addressable_shards[0].data
            self._pool_shard_bytes = 2 * int(shard.nbytes)
        else:
            self.tp_degree = 1
            self._pool_shard_bytes = 2 * int(self.pages_k.nbytes)
            if self._kv_int8:
                self._pool_shard_bytes += 2 * int(self.scales_k.nbytes)
        # lane sharding (r19): under dp>1 the slot-major host arrays
        # (logits, block tables, sampling knobs, rng keys) batch-shard
        # on the data axis — each replica group carries max_slots/dp
        # lanes.  Indivisible slot counts replicate the lanes (the
        # pool's page sharding still holds, so the long-context
        # capacity claim survives) with a WARN.
        self._lane_sharded = _dp > 1 and self.max_slots % _dp == 0
        if self._lane_sharded:
            from jax.sharding import NamedSharding as _NS, PartitionSpec as _P

            self._lane_sharding = _NS(mesh, _P(data_axis))
        else:
            self._lane_sharding = None
        if _dp > 1 and not self._lane_sharded:
            logger.warning(
                "decode lanes NOT sharded over (%r, %r): max_slots=%d "
                "is not divisible by mesh axis %r size %d — lane-major "
                "arrays replicate (pool page sharding is unaffected)",
                data_axis, model_axis, self.max_slots, data_axis, _dp,
            )
        self._logits = jnp.zeros((self.max_slots, self.vocab_size), jnp.float32)
        # rng state kept as raw key data so masked carries can jnp.where it
        self._keys = jax.random.key_data(
            jax.vmap(jax.random.key)(np.arange(self.max_slots))
        )

        # host bookkeeping — guarded by _lock
        self._lock = threading.Lock()
        # refcounted page allocator (r9).  The free list is a deque —
        # _alloc/_free are popleft/append (the old list-slice free list
        # was O(n) per alloc).  Page states (docs §5d state machine):
        #   free   — on _free_pages, refcount 0
        #   mapped — refcount == number of live streams whose block
        #            table points at it (shared prompt pages count once
        #            per stream)
        #   cached — refcount 0 BUT registered in the prefix index:
        #            parked on the _lru OrderedDict (oldest first) and
        #            reclaimed by _alloc under pressure instead of
        #            being freed eagerly on stream finish
        self._free_pages: Deque[int] = deque(range(1, self.num_pages))  # 0 = trash
        self._page_ref = np.zeros((self.num_pages,), np.int32)
        # prefix index: chain key -> _CachedPrefix (page registered as
        # the canonical holder of that token prefix; may be mapped or
        # LRU-cached), plus the reverse page -> entry map the release
        # path and the invariant checker need
        self._prefix_index: Dict[int, _CachedPrefix] = {}
        self._page_entry: Dict[int, _CachedPrefix] = {}
        self._lru: "OrderedDict[int, _CachedPrefix]" = OrderedDict()
        # SELDON_TPU_PREFIX_CACHE=0 disables (constructor arg wins);
        # default ON — automatic prefix reuse costs one hash walk per
        # admission and nothing on the decode hot loop
        if prefix_cache is None:
            prefix_cache = _knobs.flag("SELDON_TPU_PREFIX_CACHE")
        self._prefix_cache_enabled = bool(prefix_cache)
        # SELDON_TPU_PAGED_DEBUG=1: allocator state-machine audit at
        # every chunk boundary (no page simultaneously free/cached/
        # mapped; refcounts match live block tables)
        self._debug_invariants = (
            _knobs.flag("SELDON_TPU_PAGED_DEBUG")
        )
        # run queue: deque + identity membership set — O(1) end ops
        # (submit append / evict appendleft, where the old list paid
        # pop(0)/insert(0)) and O(1) membership tests (cancel's old
        # `in self._queue` scan).  Priority selection and mid-queue
        # removal still scan — O(queue) per admission, bounded by
        # max_queue in SLO mode and a head hit (first maximal element)
        # when every priority is 0, so the historical FIFO path stays
        # effectively O(1) per admission.
        # Bounded when max_queue > 0 (ctor arg wins over
        # SELDON_TPU_MAX_QUEUE; 0 = unbounded, the historical default):
        # an overflowing submit sheds already-expired queued streams
        # first, then the lowest-priority one — goodput over FIFO
        # fairness exactly when the queue is the p99 term (§10a).
        if not max_queue:
            max_queue = int(_knobs.raw("SELDON_TPU_MAX_QUEUE", "0") or 0)
        self.max_queue = max(0, int(max_queue))
        # chunked-prefill co-scheduling (r15, Sarathi-style): each
        # engine wave carries at most this many tokens, filled
        # decode-first then with page-aligned slices of pending
        # prefills — a long prompt stops monopolising waves, so
        # decoding streams keep their cadence and interactive TTFT
        # stops queueing behind batch prefills.  0 (the default) keeps
        # the historical monolithic prefill byte-for-byte.  Ctor arg
        # wins over SELDON_TPU_CHUNK_TOKEN_BUDGET; a budget below one
        # page + one decode step can't make page-aligned progress, so
        # it clamps up with a WARN rather than livelocking.
        if not chunk_token_budget:
            chunk_token_budget = int(
                _knobs.raw("SELDON_TPU_CHUNK_TOKEN_BUDGET", "0") or 0
            )
        self.chunk_token_budget = max(0, int(chunk_token_budget))
        if self.chunk_token_budget:
            floor = self.page_size + self.steps_per_call
            if self.chunk_token_budget < floor:
                logger.warning(
                    "SELDON_TPU_CHUNK_TOKEN_BUDGET=%d cannot cover one "
                    "prefill page plus one decode chunk; clamping to %d",
                    self.chunk_token_budget, floor,
                )
                self.chunk_token_budget = floor
        # batched multi-LoRA serving lane (r16, S-LoRA/Punica): a
        # slot-granular adapter factor pool next to the KV pool, per-
        # stream slot ids threaded through every engine program as a
        # TRACED index (one program per wave regardless of how many
        # distinct adapters it mixes).  0 (the default, or
        # SELDON_TPU_MAX_ADAPTERS unset) keeps the engine byte-
        # identical to the pre-adapter lowering: no pool is built and
        # no program takes the extra arguments.
        if not max_adapters:
            max_adapters = int(_knobs.raw("SELDON_TPU_MAX_ADAPTERS", "0") or 0)
        self.max_adapters = max(0, int(max_adapters))
        self._registry = weight_registry
        self._lora = None
        if self.max_adapters:
            from seldon_core_tpu.ops.lora import LoraPool

            self._lora = LoraPool(
                num_layers=num_layers, d_model=d_model,
                max_adapters=self.max_adapters, rank=int(lora_rank),
            )
        # adapter table (guarded by _lock; _adapter_io_lock serializes
        # the slow load/install path so concurrent cold admissions of
        # one adapter never double-install): name -> pool slot, per-
        # slot stream refcounts, an LRU of refcount-0 RESIDENT slots
        # (reclaimed on demand — the prefix cache's capacity-not-cost
        # discipline applied to weights), and temp pins covering the
        # submit window between residency and stream attachment (the
        # allocator audit counts them).
        self._adapter_io_lock = threading.Lock()
        self._adapter_table: Dict[str, int] = {}
        self._adapter_names: Dict[int, str] = {}
        self._adapter_ref = np.zeros((self.max_adapters + 1,), np.int32)
        self._adapter_free: List[int] = list(range(self.max_adapters, 0, -1))
        self._adapter_lru: "OrderedDict[int, str]" = OrderedDict()
        self._adapter_temp_pins: Dict[int, int] = {}
        # slots mid-install: popped from free/LRU but not yet named —
        # the device install runs OUTSIDE _lock (it must not stall the
        # decode loop), so the chunk-boundary audit needs this set to
        # account for the in-flight slot instead of calling it leaked
        self._adapter_installing: set = set()
        # engine-held registry pins: adapter names whose weights the
        # registry keeps pinned while they are resident in THIS pool
        self._adapter_reg_pinned: set = set()
        self._adapter_requests: Dict[str, int] = {}
        # per-slot adapter ids the programs gather by (slot-major, like
        # _block_tables; lanes without an adapter read slot 0 = zeros)
        self._adapter_slots = np.zeros((self.max_slots,), np.int32)
        self._queue: Deque[_Stream] = deque()
        self._queued: set = set()  # identity membership (streams are unhashable-by-value)
        self._slots: List[Optional[_Stream]] = [None] * self.max_slots
        self._block_tables = np.zeros((self.max_slots, self.pages_per_stream), np.int32)
        self._lengths = np.zeros((self.max_slots,), np.int32)
        self._next_id = 0
        self._closed = False
        # gen.* spans whose emission points sit inside _lock-held code
        # (finish/evict): queued here and flushed by step() AFTER the
        # lock drops — Tracer.record can write+flush a JSONL file, and
        # disk I/O must never run under the engine lock
        self._pending_spans: List[Tuple[_Stream, str, float, float, Dict[str, Any]]] = []
        # observability counters (exported by StreamingLM.metrics();
        # updated under _lock)
        self._counters = {"chunks": 0, "tokens": 0, "evictions": 0,
                          "stalls": 0, "prefills": 0, "completed": 0,
                          "bucketed_chunks": 0,
                          "spec_drafted": 0, "spec_accepted": 0,
                          # prefix cache (r9): per-admission hit/miss,
                          # cached pages reclaimed under pressure, and
                          # prompt tokens whose prefill was skipped
                          "prefix_hits": 0, "prefix_misses": 0,
                          "prefix_evictions": 0, "prefix_tokens_saved": 0,
                          # SLO lifecycle (r10): streams dropped by the
                          # bounded queue's shedding policy, streams
                          # whose deadline expired (queued or mid-
                          # decode), preemptive evictions for a higher-
                          # priority admission, and re-admissions of
                          # preempted streams; chunk_faults counts
                          # injected/contained chunk failures handled
                          # without fail_all
                          "shed": 0, "expired": 0, "preempted": 0,
                          "restored": 0, "chunk_faults": 0,
                          # drain/handoff (r12): live streams journaled
                          # by drain() for a respawned engine, and
                          # journal entries replay() re-submitted here
                          "drained": 0, "replayed": 0,
                          # chunked prefill (r15): prompt tokens whose
                          # KV was COMPUTED by prefill programs (cache
                          # hits and KV imports excluded) and the
                          # number of prefill device calls — with
                          # "tokens" (decode) this is the
                          # prefill/decode split the flight-recorder
                          # chunk records carry per wave
                          "prefill_tokens": 0, "prefill_chunks": 0,
                          # disaggregation (r15): prefills exported as
                          # KV-page handoff payloads, and imported
                          # payloads scatter-written into this pool
                          "kv_exports": 0, "kv_imports": 0,
                          # live migration + quarantine (r17): mid-
                          # decode streams exported to / imported from
                          # a peer engine without losing a token, and
                          # streams retired by the post-chunk NaN/Inf
                          # screen (500 NUMERIC_POISON — never
                          # fail_all on the wave)
                          "migrated_out": 0, "migrated_in": 0,
                          "quarantined": 0,
                          # multi-LoRA (r16): adapter pool-slot loads /
                          # LRU reclaims, submit-time residency hit or
                          # cold-load miss, and waves whose runnable
                          # lanes mixed >= 2 distinct adapter slots
                          # (the grouped-matmul case — still ONE
                          # compiled program, which is the point)
                          "adapter_loads": 0, "adapter_evictions": 0,
                          "adapter_hits": 0, "adapter_misses": 0,
                          "multi_adapter_chunks": 0,
                          # wall seconds inside device calls + readback,
                          # split by phase: decode-rate observability
                          # (tokens / chunk_wall_s) independent of
                          # admission cost
                          "chunk_wall_s": 0.0, "prefill_wall_s": 0.0,
                          # per-request cost ledger (r20): totals accrued
                          # once per stream at termination (finish/fail/
                          # export/migrate-out), so the per-adapter split
                          # below sums to these EXACTLY.  page_seconds is
                          # the KV occupancy integral (pages held x wall
                          # seconds, stamped at every page-count change);
                          # the token pair is work ATTRIBUTED to streams
                          # (re-derived work after eviction counts —
                          # it is cost, unlike the dedup'd counters
                          # above).  Keys absent from engine_stats when
                          # SELDON_TPU_TELEMETRY=0.
                          "cost_page_seconds": 0.0,
                          "cost_prefill_tokens": 0,
                          "cost_decode_tokens": 0,
                          # black-box capture plane (r21): capture
                          # containers written to the store.  Key absent
                          # from engine_stats when SELDON_TPU_CAPTURE=0
                          # (with capture_store_bytes — the off lane
                          # sheds every new key).
                          "captures": 0,
                          # hierarchical KV tier (r22): pages demoted
                          # into the host tier / chains promoted back
                          # through the scatter import, promoted pages
                          # per level, uncached full pages the tier
                          # ALSO missed (the hit-rate denominator's
                          # other half), entries the tier byte budgets
                          # pushed out entirely, and the container
                          # byte flow both directions.  All keys absent
                          # from engine_stats when
                          # SELDON_TPU_KV_OFFLOAD=0 (with the two
                          # kv_tier_*_bytes gauges — the off lane sheds
                          # every new key).
                          "kv_tier_demotions": 0, "kv_tier_promotions": 0,
                          "kv_tier_host_hits": 0, "kv_tier_disk_hits": 0,
                          "kv_tier_misses": 0, "kv_tier_evictions": 0,
                          "kv_tier_bytes_demoted": 0,
                          "kv_tier_bytes_promoted": 0}
        # per-adapter cost ledger split (adapter None -> "base"): dict
        # name -> {page_seconds, prefill_tokens, decode_tokens, streams}
        # exported with adapter labels by the bridge (bridge-excluded
        # from the flat mapping, like adapter_requests)
        self._cost_by_adapter: Dict[str, Dict[str, Any]] = {}
        # injectable monotonic clock for the occupancy integral: the
        # exactness test drives it manually so page-seconds compare
        # EQUAL to a hand-computed integral, not approximately
        import time as _time_mod

        self._cost_clock = _time_mod.monotonic
        self._telemetry_enabled = _telemetry.telemetry_enabled()

        # ---- observability: flight recorder + profiler hook (r7) ----
        # Per-chunk ring buffer (near-zero overhead: one dict append per
        # CHUNK, not per step) exposed via engine_stats(detail=True) and
        # the gateway's /debug/engine; SELDON_TPU_FLIGHT_RECORDER=0
        # disables (the bench's obs-off arm), any other value sets the
        # ring capacity.  SELDON_TPU_DUMP_P99_MS breached by the ring's
        # chunk-wall p99 auto-dumps the ring to JSONL under
        # SELDON_TPU_DUMP_DIR — post-incident forensics with no profiler
        # attached.
        rec_env = _knobs.raw("SELDON_TPU_FLIGHT_RECORDER", "")
        self.recorder = None
        if rec_env != "0":
            from seldon_core_tpu.utils.flightrec import FlightRecorder

            self.recorder = FlightRecorder(
                capacity=int(rec_env) if rec_env.isdigit() and rec_env != "0"
                else 512,
                dump_p99_ms=float(
                    _knobs.raw("SELDON_TPU_DUMP_P99_MS", "0") or 0
                ),
                dump_dir=_knobs.raw("SELDON_TPU_DUMP_DIR") or None,
            )
        # ---- per-request black-box capture (r21) ----
        # Default-off forensics plane: when armed, terminating requests
        # matching a trigger (every Nth via head sampling, every error,
        # every puid active in a p99-breach window) are serialized as
        # SRT1 capture containers into the bounded on-disk store.  The
        # off lane carries NO capture state on the hot path.
        from seldon_core_tpu.utils import capture as _capture_mod

        self._capture_enabled = _capture_mod.capture_enabled()
        self._capture_sample = (
            _capture_mod.sample_every() if self._capture_enabled else 0
        )
        self._capture_seen = 0  # head-sampling request counter
        self._capture_lock = threading.Lock()
        # puids seen in breach-dump windows, pending capture at their
        # stream's termination (bounded FIFO — a breach marks at most
        # one ring's worth of requests)
        self._breach_puids: "OrderedDict[str, float]" = OrderedDict()
        if self._capture_enabled and self.recorder is not None:
            self.recorder.on_dump = self._note_breach_puids
        # ---- hierarchical KV tier (r22) ----
        # Default-off host-RAM (+ optional disk) demotion target for
        # LRU-reclaimed prefix pages: _evict_cached_locked stages the
        # reclaimed page, the next flush point gathers it host-side
        # into an SRT1 container, and a later admission's chain walk
        # promotes it back through the donated-scatter import — no
        # prefill FLOPs.  The off lane carries None and an always-empty
        # staging list: no new device programs, stats keys shed.
        self._kv_tier = None
        self._tier_pending: List[Tuple[int, int, Tuple[int, ...], int]] = []
        if _knobs.flag("SELDON_TPU_KV_OFFLOAD"):
            from seldon_core_tpu.models.kvtier import HostKvTier

            self._kv_tier = HostKvTier(
                budget_bytes=int(
                    float(
                        _knobs.raw("SELDON_TPU_KV_HOST_BUDGET_GIB", "4")
                        or 4
                    ) * (1 << 30)
                ),
                spill_dir=_knobs.raw("SELDON_TPU_KV_SPILL_DIR") or None,
                spill_budget_bytes=int(
                    float(_knobs.raw("SELDON_TPU_KV_SPILL_GIB", "16") or 16)
                    * (1 << 30)
                ),
            )
        # opt-in XLA-level inspection: the first N decode chunks run
        # inside jax.profiler.trace (N = SELDON_TPU_PROFILE_CHUNKS,
        # default 4) writing to SELDON_TPU_PROFILE_DIR — enough to catch
        # the compiled chunk program's timeline without profiling the
        # whole serving lifetime
        self._profile_dir = _knobs.raw("SELDON_TPU_PROFILE_DIR") or None
        self._profile_chunks_left = (
            int(_knobs.raw("SELDON_TPU_PROFILE_CHUNKS", "4"))
            if self._profile_dir else 0
        )
        self._profile_started = False

        # speculative mode: per-slot draft/verify INSIDE the batched
        # engine — each chunk is ONE verify forward of width draft_k+1
        # per slot instead of steps_per_call sequential decode steps.
        # Greedy bit-exactness per stream is preserved: every emitted
        # token is the model's own argmax (drafts only decide how many
        # argmaxes one forward confirms), so speculative and plain
        # decode produce identical ids (asserted in tests).
        self.speculative = dict(speculative) if speculative else None
        if self.speculative is not None:
            draft = self.speculative.setdefault("draft", "ngram")
            if draft not in ("ngram", "oracle", "model"):
                # 'oracle' = caller-supplied continuation hints
                # (submit(draft_hint=...)) — the acceptance-ceiling
                # benchmarking lane; 'model' = a small trained draft LM
                raise ValueError(
                    "PagedEngine speculative mode supports draft='ngram', "
                    "draft='oracle' or draft='model'"
                )
            self.speculative.setdefault("draft_k", 4)
            self.speculative.setdefault("ngram", 2)
            self.draft_k = int(self.speculative["draft_k"])
            if self.draft_k < 1:
                raise ValueError("speculative draft_k must be >= 1")
            if draft == "model":
                # draft-model lane: a small LM proposes k tokens per
                # round from a sliding context window (stateless — no
                # second KV pool to manage; the window re-forward is
                # cheap because the draft is small).  Draft quality only
                # moves ACCEPTANCE: every emitted token is still the
                # target's own argmax via the verify forward, so a bad
                # draft degrades speed, never output.
                if self.speculative.get("draft_params") is None:
                    raise ValueError(
                        "draft='model' needs draft_params (and usually "
                        "draft_config={vocab_size,d_model,num_layers,...})"
                    )
                from seldon_core_tpu.models.transformer import TransformerLM

                dc = dict(self.speculative.get("draft_config") or {})
                dc.setdefault("vocab_size", self.vocab_size)
                if int(dc["vocab_size"]) != self.vocab_size:
                    raise ValueError(
                        "draft model must share the target's vocab_size"
                    )
                self.draft_window = int(self.speculative.get("draft_window", 64))
                dc.setdefault("max_len", self.draft_window)
                if int(dc["max_len"]) < self.draft_window:
                    raise ValueError(
                        "draft_config.max_len must cover draft_window"
                    )
                self._draft_module = TransformerLM(dtype=dtype, **dc)
                self._draft_params = self.speculative["draft_params"]

        # poison-stream quarantine (r17): a cheap post-chunk isfinite
        # reduction over served logits retires ONLY the offending
        # stream with 500 NUMERIC_POISON — one NaN lane must never
        # stream garbage or take its wave-mates down.
        # SELDON_TPU_NAN_GUARD=0 disables the screen.
        self._nan_guard = _knobs.flag("SELDON_TPU_NAN_GUARD")
        self._isfinite_jit = None  # built lazily on first screened chunk

        # device-health watchdog (r17): per-wave wall time / fault rate
        # / compile storms / allocator pressure drive the healthy ->
        # degraded -> evacuating state machine the evacuation layer
        # reads (utils/watchdog.py; SELDON_TPU_WATCHDOG=0 disables —
        # the engine then always reports healthy)
        from seldon_core_tpu.utils.watchdog import (
            EngineWatchdog,
            watchdog_enabled,
        )

        self._watchdog = EngineWatchdog() if watchdog_enabled() else None
        self._wd_last_compiles = 0

        # recompilation sentinels: every engine jit entry point reports
        # compile events to seldon_tpu_jit_compiles_total{program=} +
        # a WARN naming the triggering shape signature — a silent
        # under-traffic recompile is the classic invisible TPU tail
        # (utils/jitwatch.py; SELDON_TPU_JIT_SENTINEL=0 disables)
        from seldon_core_tpu.utils.jitwatch import JitSentinel

        self._sentinels = {
            name: JitSentinel(name)
            for name in ("paged_chunk", "paged_prefill", "paged_spec_chunk",
                         "paged_draft_rollout")
        }
        if self.speculative is not None and draft == "model":
            self._draft_rollout = self._sentinels["paged_draft_rollout"].wrap(
                jax.jit(self._draft_rollout_fn)
            )

        self._prefill_jit: Dict[Tuple[int, int], Any] = {}  # (bucket, k)
        # cached-prefix suffix prefill: (suffix bucket, k, read pages)
        self._prefill_cached_jit: Dict[Tuple[int, int, int], Any] = {}
        # disaggregated KV import: pages-per-payload -> donated scatter
        self._import_kv_jit: Dict[int, Any] = {}
        # (steps, bucket spec) -> compiled chunk program, where the
        # bucket spec is a static tuple of (lane_count, ctx_pages)
        # pairs (one entry = uniform, two = the length-bucketed gather)
        self._chunk_jit: Dict[Tuple[int, Tuple[Tuple[int, int], ...]], Any] = {}
        # one fixed-shape program deriving every slot's rng key data
        self._derive_keys = jax.jit(
            jax.vmap(lambda s: jax.random.key_data(jax.random.key(s)))
        )
        self._spec_chunk = (
            self._sentinels["paged_spec_chunk"].wrap(
                self._tp_jit(
                    self._spec_chunk_fn, n_rep_in=5,
                    out_spec=("lane", "lane", "pool", "pool", "lane"),
                    lora=True, lane_hosts=True,
                )
            )
            if self.speculative is not None else None
        )

    # ---- jitted programs --------------------------------------------------

    def _write_kv(self, pk, pv, new_k, new_v, block_row_or_tables, start, valid,
                  from_zero: bool = False):
        return write_kv(
            pk, pv, new_k, new_v, block_row_or_tables, start, valid,
            page_size=self.page_size, max_len=self.max_len, from_zero=from_zero,
        )

    def _kv_args(self):
        """The pool arguments every jitted program takes: bare arrays
        for the native pool, ``(pages, scales)`` bundles for the int8
        pool (r18) — one argument convention, the programs split at
        entry (:func:`kv_split`)."""
        if self._kv_int8:
            return (self.pages_k, self.scales_k), (self.pages_v, self.scales_v)
        return self.pages_k, self.pages_v

    def _store_kv(self, pk, pv):
        """Inverse of :meth:`_kv_args` for a program's returned pools."""
        if self._kv_int8:
            (self.pages_k, self.scales_k), (self.pages_v, self.scales_v) = pk, pv
        else:
            self.pages_k, self.pages_v = pk, pv

    def _lane_put(self, x):
        """Pin a carried slot-major device array to the lane sharding.

        The decode chunk's in_shardings batch-shard lane arrays on the
        ``data`` axis, but jit refuses COMMITTED args whose sharding
        differs — and ``self._logits``/``self._keys`` arrive committed
        from the prefill program (replicated) or from host-side
        ``.at[].set`` edits.  Steady state this is a no-op (device_put
        short-circuits on an equal sharding); after a prefill it is the
        one reshard copy that moves the new lane onto its shard.
        Single-chip and 1-D-mesh engines return ``x`` untouched."""
        if self._lane_sharding is None:
            return x
        return self._jax.device_put(x, self._lane_sharding)

    def _materialize(self, params):
        """Once-per-program dequant of int8 weights (no-op for fp).
        Call at program ENTRY, never inside a scan step — per-step
        dequant does not fuse and measured 0.48x on TPU.  w8a8
        dequantises to f32 so the W8A8 layers' in-graph re-quantisation
        reproduces the at-rest integers exactly (a bf16 intermediate
        double-rounds them by ±1)."""
        from seldon_core_tpu.ops.surgery import materialize

        dtype = self._jnp.float32 if self.precision == "w8a8" else self._dtype
        return materialize(params, self.quantize, dtype)

    def _tp_jit(self, fn, *, n_rep_in: int, out_spec: Sequence[str],
                donate_argnums: Tuple[int, ...] = (1, 2),
                lora: bool = False, lane_hosts: bool = False):
        """jit an engine program, annotated for GSPMD under the
        serving mesh (1-D ``{model}`` or 2-D ``{data, model}``).

        Every engine program shares one argument convention — ``(params,
        pk, pv, *host_arrays)`` — so one helper covers the prefill, the
        cached-suffix prefill, the bucketed chunk, and the speculative
        verify: params pin their megatron specs (naming only the
        ``model`` axis, so under a 2-D mesh ONE weight residency is
        shared — replicated — across the data axis's replica groups),
        pools pin the page+heads-sharded layout (in AND out, so the
        donated buffers round-trip without a resharding copy per call),
        and everything else is pinned per ``lane_hosts``:

        * ``lane_hosts=False`` (prefills, KV import) — host arrays are
          explicitly replicated; prefill batches are ragged joiner
          groups, not the slot array, so they don't batch-shard.
        * ``lane_hosts=True`` (decode chunk, speculative verify) — the
          slot-major host arrays (and ``"lane"`` outputs) shard their
          lane dim 0 on the ``data`` axis when the engine runs dp>1
          with a divisible slot count; otherwise ``lane`` degenerates
          to the replicated sharding, so 1-D-mesh programs keep the
          PR 7 annotation spelling VALUE-IDENTICAL (the byte-identity
          bar the lowering tests assert).

        Block tables ride the lane rule: each data shard owns its own
        lanes' tables, while the pages they index live page-sharded
        across the axis — GSPMD partitions the pool gather/scatter
        (partial gather + mask + all-reduce; zeros sum bit-exactly in
        f32, which is why (2,2) greedy stays bit-exact vs TP-only).
        Pinning the whole signature keeps the partitioner
        deterministic: one GSPMD program, collectives inserted by XLA,
        no propagation choices left to vary run-to-run.

        ``mesh=None`` returns the EXACT historical ``jax.jit`` call —
        no annotation objects are even constructed — so TP=1 programs
        stay byte-identical to the pre-TP engine (asserted by the
        no-collectives lowering test).

        ``lora=True`` marks a program that takes the multi-LoRA
        trailing arguments ``(factor pools, adapter_idx)`` WHEN the
        engine has adapters enabled — the pools pin the megatron-
        following shardings ``LoraPool.shardings`` spells (A col- /
        B row-parallel with their base layer), the index replicates.
        With adapters off nothing is appended and the signature (and
        lowering) is byte-identical to the pre-adapter engine."""
        jax = self._jax
        if self._mesh is None:
            return jax.jit(fn, donate_argnums=donate_argnums)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self._mesh, P())
        lane = (
            self._lane_sharding
            if lane_hosts and self._lane_sharding is not None else rep
        )
        pool = self.pages_k.sharding
        # leaves the shard_params guard left host-side have no sharding:
        # replicate them explicitly
        param_sh = jax.tree.map(
            lambda x: getattr(x, "sharding", rep), self.params
        )
        in_sh: Tuple[Any, ...] = (param_sh, pool, pool) + (lane,) * n_rep_in
        if lora and self._lora is not None:
            in_sh = in_sh + (
                self._lora.shardings(self._mesh, self._model_axis), rep,
            )
        return jax.jit(
            fn,
            donate_argnums=donate_argnums,
            in_shardings=in_sh,
            out_shardings=tuple(
                pool if o == "pool" else lane if o == "lane" else rep
                for o in out_spec
            ),
        )

    def _build_prefill(self, bucket: int, k: int):
        """Prefill program for ``k`` same-bucket prompts in ONE call.

        Admission cost through a high-latency host link is per device
        CALL, not per prompt: 16 joiners prefilled one-by-one pay 16
        round-trips; batched they pay one.  Pad rows (``true_lens`` 1,
        block row 0) write only the trash page."""
        jax, jnp = self._jax, self._jnp

        def prefill(params, pk, pv, tokens, true_lens, block_rows,
                    lora=None, adapter_idx=None):
            # tokens: (k, bucket)  true_lens: (k,)  block_rows: (k, P)
            # lora/adapter_idx: the multi-LoRA trailing args (engines
            # with adapters enabled only — pad rows carry slot 0)
            params = self._materialize(params)
            positions = jnp.broadcast_to(jnp.arange(bucket)[None, :], (k, bucket))
            lengths = jnp.zeros((k,), jnp.int32)
            pk_pages, sk = kv_split(pk)
            pv_pages, sv = kv_split(pv)
            logits, nk, nv = self.module.apply(
                {"params": params}, tokens, positions, pk_pages, pv_pages,
                block_rows, lengths, lora=lora, adapter_idx=adapter_idx,
                kv_scales=kv_scales_arg(sk, sv),
            )
            valid = jnp.arange(bucket)[None, :] < true_lens[:, None]
            pk, pv = self._write_kv(
                pk, pv, nk, nv, block_rows, jnp.zeros((k,), jnp.int32), valid,
                from_zero=True,
            )
            last = logits[jnp.arange(k), true_lens - 1]  # (k, vocab)
            return last, pk, pv

        return self._sentinels["paged_prefill"].wrap(
            self._tp_jit(prefill, n_rep_in=3, out_spec=("rep", "pool", "pool"),
                         lora=True),
            static=f"bucket={bucket},k={k}",
        )

    def _build_prefill_cached(self, bucket: int, k: int, rp: int):
        """Suffix prefill for ``k`` streams whose leading prompt pages
        were matched in the prefix cache: only the UNCACHED tail
        prefills (``bucket`` covers the longest suffix in the group),
        attending over the shared prefix pages through the same
        block-table gather decode already uses.

        ``rp`` is the static read-table width (pages covering the
        group's longest cached prefix, power-of-two so the compile
        count stays logarithmic like every other shape axis here).
        Writes go through a SHIFTED table — row ``j`` of ``write_rows``
        is the page the suffix's j-th block lands in — so the page-block
        DUS fast path applies unchanged: cached lengths are page-aligned
        by construction, so every suffix write starts at page offset 0.
        Pad rows (``true_lens`` 1, ``cached_lens`` 0, zero tables) write
        only the trash page, exactly like the plain prefill."""
        jax, jnp = self._jax, self._jnp

        def prefill(params, pk, pv, tokens, true_lens, cached_lens,
                    read_rows, write_rows, lora=None, adapter_idx=None):
            # tokens: (k, bucket) suffix tokens  true_lens: (k,) suffix
            # lengths  cached_lens: (k,) tokens already resident in
            # shared pages  read_rows: (k, rp)  write_rows: (k, wp)
            params = self._materialize(params)
            positions = cached_lens[:, None] + jnp.arange(bucket)[None, :]
            pk_pages, sk = kv_split(pk)
            pv_pages, sv = kv_split(pv)
            logits, nk, nv = self.module.apply(
                {"params": params}, tokens,
                jnp.minimum(positions, self.max_len - 1),
                pk_pages, pv_pages, read_rows, cached_lens,
                lora=lora, adapter_idx=adapter_idx,
                kv_scales=kv_scales_arg(sk, sv),
            )
            valid = jnp.arange(bucket)[None, :] < true_lens[:, None]
            pk, pv = self._write_kv(
                pk, pv, nk, nv, write_rows, jnp.zeros((k,), jnp.int32), valid,
                from_zero=True,
            )
            last = logits[jnp.arange(k), true_lens - 1]  # (k, vocab)
            return last, pk, pv

        return self._sentinels["paged_prefill"].wrap(
            self._tp_jit(prefill, n_rep_in=5, out_spec=("rep", "pool", "pool"),
                         lora=True),
            static=f"cached,bucket={bucket},k={k},rp={rp}",
        )

    def _sample_batch(self, logits, keys, temps, top_ks):
        """All-slot sampling — same per-slot semantics as
        Generator.sample, restructured so the expensive branch is a
        SCALAR-predicate ``lax.cond``.  A per-slot ``vmap(lax.cond)``
        lowers to select — BOTH branches execute every step, so pure
        greedy decode (the common serving case) was paying a full
        (slots, vocab) sort + categorical per token; measured on TPU
        this was the dominant per-step cost of the chunk program at 16
        slots.  With the scalar cond, the sort runs only when some
        live slot actually samples."""
        jax, jnp = self._jax, self._jnp

        greedy = jnp.argmax(logits, axis=-1)

        def draw_slot(logits_i, key_i, temp_i, top_k_i):
            scaled = logits_i / jnp.maximum(temp_i, 1e-6)
            k = jnp.where(top_k_i > 0, top_k_i, logits_i.shape[-1])
            kth = -jnp.sort(-scaled)
            cutoff = kth[k - 1]
            masked = jnp.where(scaled >= cutoff, scaled, -jnp.inf)
            return jax.random.categorical(key_i, masked)

        def draw_all(_):
            sampled = jax.vmap(draw_slot)(logits, keys, temps, top_ks)
            return jnp.where(temps > 0, sampled, greedy)

        return jax.lax.cond(
            jnp.any(temps > 0), draw_all, lambda _: greedy, None
        )

    def _pages_horizon(self, runnable: List[_Stream], per_chunk: int) -> int:
        """Block-table columns the next chunk actually needs.

        The paged attention GATHERS every table column it is given each
        step, so passing the full worst-case table makes short streams
        pay max_len-sized HBM traffic (measured: the dominant cost of
        the chunk program at 16 slots).  Slice to the live horizon —
        the largest runnable stream's length plus this chunk — rounded
        up to a power of two so jit sees a log-bounded set of shapes
        (each is its own compiled program; a warm pass over a stream's
        growth covers them).  Lanes masked done may hold longer
        contexts than the slice; their compute is discarded (writes go
        to the trash page, sampled tokens are overwritten), so the
        truncated gather they see is harmless."""
        if not runnable:
            return 1
        need = max(int(self._lengths[s.slot]) for s in runnable) + per_chunk
        return self._pages_pow2(-(-need // self.page_size))

    def _pages_pow2(self, need_pages: int) -> int:
        """Round a page count up to a power of two, capped at the
        per-stream table width — the one shared rounding rule, so
        prefill and decode always land on the same compiled shapes."""
        p = 1
        while p < need_pages:
            p *= 2
        return min(p, self.pages_per_stream)

    def _plan_buckets(
        self, runnable: List[_Stream], steps: int, pages_h: int
    ) -> Tuple[Tuple[Tuple[int, int], ...], np.ndarray]:
        """Static bucket spec + lane permutation for the next chunk.

        Splits the slot array in half (bucket sizes are STATIC —
        max_slots//2 — so the compile count stays bounded by the two
        horizon ladders; membership moves between chunks via the traced
        permutation).  The split point among LIVE streams is their own
        midpoint: the shorter half of the runnable lanes anchors bucket
        0, the longer half bucket 1, and idle/stalled lanes (whose
        compute is discarded either way) are FILLER for the remaining
        capacity of each bucket — under partial occupancy the live
        short streams therefore still get the short horizon instead of
        being displaced into the long bucket by idle lanes, and a
        bucketed chunk always means some live lane actually runs
        cheaper (the ``bucketed_chunks`` counter cannot overstate
        engagement).  Horizons are per-bucket power-of-two page counts
        over the bucket's RUNNABLE lanes (ring impl: pages existing at
        chunk start; pool impl: + this chunk's growth, since in-chunk
        tokens are read back from the pool).  Degenerates to one bucket
        — the exact pre-bucketing program — whenever both horizons
        agree (uniform traffic), bucketing is disabled, or fewer than 2
        lanes run.
        """
        B = self.max_slots
        ident = np.arange(B, dtype=np.int32)
        grow = steps if self._chunk_impl == "pool" else 0

        def h_of(ctx_tokens: int) -> int:
            need = ctx_tokens + grow
            return min(
                self._pages_pow2(max(1, -(-need // self.page_size))), pages_h
            )

        if not runnable:
            return ((B, 1),), ident
        h_all = h_of(max(int(self._lengths[s.slot]) for s in runnable))
        if self._ctx_buckets < 2 or B < 2 or len(runnable) < 2:
            return ((B, h_all),), ident
        B0 = B // 2
        run_lanes = sorted(
            (int(self._lengths[s.slot]), s.slot) for s in runnable
        )
        k0 = min(len(run_lanes) // 2, B0)
        h0 = h_of(run_lanes[k0 - 1][0]) if k0 else 1
        h1 = h_of(run_lanes[-1][0])
        if h0 == h1:
            return ((B, h_all),), ident
        live = {g for _, g in run_lanes}
        idle = [g for g in range(B) if g not in live]
        fill0 = B0 - k0  # >= 0, and len(idle) >= fill0 (B1 >= ceil(n_r/2))
        order = np.asarray(
            [g for _, g in run_lanes[:k0]] + idle[:fill0]
            + [g for _, g in run_lanes[k0:]] + idle[fill0:],
            np.int32,
        )
        return ((B0, h0), (B - B0, h1)), order

    def _get_chunk(self, steps: int, buckets: Tuple[Tuple[int, int], ...]):
        """Compiled decode program for one (ladder size, bucket spec)
        pair (lazy, cached).  ``buckets`` is a static tuple of
        ``(lane_count, ctx_pages)`` pairs summing to ``max_slots`` —
        one entry for the uniform case, two for the length-bucketed
        gather (lanes arrive bucket-sorted via the chunk's ``perm``
        argument).  For the ring impl ``ctx_pages`` is the bucket's
        gathered-context horizon; for the pool impl it is the per-step
        table width (context + this chunk's growth).  Both axes are
        power-of-two-bounded, so the compile count stays logarithmic."""
        key = (steps, buckets)
        fn = self._chunk_jit.get(key)
        if fn is None:
            fn = self._sentinels["paged_chunk"].wrap(
                self._chunk_program(steps, buckets),
                static=f"steps={steps},buckets={buckets}",
            )
            self._chunk_jit[key] = fn
        return fn

    def _chunk_program(self, steps: int, buckets: Tuple[Tuple[int, int], ...]):
        """The jitted (un-sentineled) decode chunk for one static spec —
        body selection + the TP annotation spelling live HERE only,
        shared by the serving path (`_get_chunk`) and the audit surface
        (`lower_chunk`)."""
        from functools import partial

        if self._chunk_impl == "pool":
            body = partial(self._chunk_fn_pool, steps, buckets)
        else:
            body = partial(self._chunk_fn, steps, buckets)
        return self._tp_jit(
            body, n_rep_in=11,
            out_spec=("lane", "pool", "pool", "lane", "lane", "lane",
                      "lane", "lane"),
            lora=True, lane_hosts=True,
        )

    def lower_chunk(self, steps: int, buckets: Tuple[Tuple[int, int], ...]):
        """Lower the decode chunk through the serving path's own
        program builder (same body selection, same ``_tp_jit``
        annotation via ``_chunk_program``) against representative
        arguments — the audit surface ``tools/profile_paged_tp.py`` and
        the TP=1 byte-identical / no-collectives lowering tests share,
        so the audited annotation spelling can never drift from the
        served program.  The block-table width is the max bucket
        horizon — representative, not necessarily a specialization the
        scheduler has compiled (serving slices tables to its own pow2
        page horizon per call)."""
        jax, jnp = self._jax, self._jnp
        B = self.max_slots
        horizon = max(h for _, h in buckets)

        def pool_arg(p):
            # ABSTRACT pool args: lowering must never allocate a second
            # full pool next to the live one (and under TP a concrete
            # jnp.zeros would materialise it unsharded on one device —
            # exactly what shard_decode_state exists to prevent).  The
            # int8 pool's (pages, scales) bundle abstracts leaf-wise.
            if isinstance(p, tuple):
                return tuple(pool_arg(x) for x in p)
            if self._mesh is not None:
                return jax.ShapeDtypeStruct(p.shape, p.dtype,
                                            sharding=p.sharding)
            return jax.ShapeDtypeStruct(p.shape, p.dtype)

        kv_k, kv_v = self._kv_args()
        ex = (
            self.params,
            pool_arg(kv_k),
            pool_arg(kv_v),
            jnp.zeros((B, self.vocab_size), jnp.float32),
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B, horizon), jnp.int32),
            jax.random.key_data(
                jax.vmap(jax.random.PRNGKey)(
                    jnp.arange(B, dtype=jnp.uint32))),
            jnp.zeros((B,), bool),
            jnp.zeros((B,), jnp.int32),
            jnp.full((B,), 8, jnp.int32),
            jnp.zeros((B,), jnp.float32),
            jnp.zeros((B,), jnp.int32),
            jnp.full((B,), -1, jnp.int32),
            jnp.arange(B, dtype=jnp.int32),
        )
        if self._lora is not None:
            # adapters enabled: the served program takes the factor
            # pools + per-lane slot ids, so the audit must lower the
            # same signature (zeros index = every lane on the zero
            # adapter — representative, same lowering as any mix)
            ex = ex + (
                self._lora.device_args(), jnp.zeros((B,), jnp.int32),
            )
        return self._chunk_program(steps, buckets).lower(*ex)

    def _chunk_fn(
        self, steps, buckets, params, pk, pv, logits, lengths, block_tables,
        keys, done, emitted, max_new, temps, top_ks, eos_ids, perm,
        lora=None, adapter_idx=None,
    ):
        """``steps`` decode steps for all slots, on device — the ring
        implementation (r5 default).

        The legacy implementation gathered every slot's pages from the
        pool EVERY step and DUS-wrote the pool every step; the r5
        slot-scaling probe measured that per-step gather at 3.2 ms/step
        (64 slots) -> 18.4 ms/step (128 slots, 13.7x its traffic
        floor), plus several ms/step of pool read/write-hazard
        overhead — the cause of the 64->128 stream throughput
        regression.  Here the pool is touched exactly twice per chunk:

        1. **ctx gather, once** — each slot's context K/V (positions
           < len0) is gathered into a contiguous ``(L, Bb, Cb, h, hd)``
           buffer PER LENGTH BUCKET (``buckets`` — r6): lanes arrive
           permuted bucket-sorted via ``perm`` and each bucket gathers
           only ITS horizon's pages, so under mixed-length traffic the
           short streams stop paying the longest stream's gather AND
           per-step ctx-einsum cost.  Amortised over ``steps``.
        2. **page write-back, once** — the chunk's new K/V accumulate
           in a step-indexed ring (column t at step t: ONE uniform DUS
           per step, no per-slot raggedness) and land in their pages
           in page-block DUS writes at chunk end (a lax.scan over
           each bucket's slots keeps the program small).

        Per-step attention is therefore three dense einsums (ctx, ring,
        self) per bucket — same token set, masks, and dtypes as the
        pool path, so greedy outputs stay exact (asserted by the parity
        suite; a lane's attention never depends on which bucket its
        co-batch landed in).  Memory cost: the ctx copy (≈ the live
        context's size, now right-sized per bucket) for the chunk's
        duration — the classic paged-storage / contiguous-working-set
        split.
        """
        jax, jnp = self._jax, self._jnp
        # dequant ONCE per chunk, amortised over steps_per_call decode
        # steps (int8 halves resident weight HBM; measured on TPU,
        # per-step dequant does not fuse and ran 0.48x)
        params = self._materialize(params)
        L = self.module.num_layers
        B = self.max_slots
        h = self.module.num_heads
        hd = self.module.d_model // self.module.num_heads
        ps = self.page_size
        dtype = pk.dtype

        multi = len(buckets) > 1
        if multi:
            # bucket-sort every per-slot carry; outputs un-permute at
            # exit so the engine's state stays slot-major.  perm is a
            # TRACED argument — bucket membership changes chunk to
            # chunk without recompiling (only the static (lanes,
            # horizon) spec keys the program).
            inv_perm = jnp.argsort(perm)
            (logits, lengths, block_tables, keys, done, emitted, max_new,
             temps, top_ks, eos_ids) = (
                a[perm] for a in (
                    logits, lengths, block_tables, keys, done, emitted,
                    max_new, temps, top_ks, eos_ids)
            )
            if adapter_idx is not None:
                adapter_idx = adapter_idx[perm]

        len0 = lengths  # frozen at chunk start: ctx mask + write-back base
        # POOL layout: flat (L, pages, ps, d) by default (halves HBM —
        # the split trailing dims pad 2x under the TPU tile) or split
        # (L, pages, ps, h, hd) in kernel mode.  WORKING-SET layout:
        # always split — measured end-to-end, the per-step dense ctx
        # reads run ~1.5x faster against the split buffer (flat ctx
        # repacked per step for the attention einsums: 13.9k vs 21.2k
        # tok/s at 128 streams), while the pool's at-rest layout only
        # matters for the once-per-chunk gather and write-back.  So:
        # flat at rest, split in flight.
        tail = tuple(pk.shape[3:])
        # per bucket: (L, Bb, Pb, ps, *tail) -> split (L, Bb, Cb, h, hd)
        ctx_k, ctx_v = [], []
        off = 0
        for nb, hb in buckets:
            tb = block_tables[off:off + nb, :hb]
            Cb = hb * ps
            ctx_k.append(pk[:, tb].reshape(L, nb, Cb, h, hd))
            ctx_v.append(pv[:, tb].reshape(L, nb, Cb, h, hd))
            off += nb
        ctx_k, ctx_v = tuple(ctx_k), tuple(ctx_v)
        if not multi:
            ctx_k, ctx_v = ctx_k[0], ctx_v[0]
        ring_k = jnp.zeros((L, B, steps, h, hd), dtype)
        ring_v = jnp.zeros((L, B, steps, h, hd), dtype)

        def step(carry, t):
            logits, lengths, keys, done, emitted, ring_k, ring_v = carry
            typed = jax.random.wrap_key_data(keys)
            split = jax.vmap(jax.random.split)(typed)
            step_keys = split[:, 1]
            token = self._sample_batch(logits, step_keys, temps, top_ks)
            active = ~done
            # inactive lanes (finished OR stalled on pool pressure) must
            # keep their carries intact: a stalled stream resumes from
            # exactly the logits/rng state it stalled with
            keys = jnp.where(
                active[:, None], jax.random.key_data(split[:, 0]), keys
            )
            token = jnp.where(active, token, eos_ids)
            emitted = emitted + active.astype(jnp.int32)
            done = done | (token == eos_ids) | (emitted >= max_new)
            positions = lengths[:, None]  # new token's absolute position
            new_logits, nk, nv = self.chunk_module.apply(
                {"params": params}, token[:, None],
                jnp.minimum(positions, self.max_len - 1),
                ctx_k, ctx_v, ring_k, ring_v, t, len0,
                lora=lora, adapter_idx=adapter_idx,
            )
            # ring col t <- this step's K/V: ONE uniform DUS (inactive
            # lanes write garbage there; never written back — emitted
            # caps the write-back, and lanes go inactive monotonically
            # within a chunk so accepted ring cols are 0..emitted-1)
            ring_k = jax.lax.dynamic_update_slice(ring_k, nk, (0, 0, t, 0, 0))
            ring_v = jax.lax.dynamic_update_slice(ring_v, nv, (0, 0, t, 0, 0))
            logits = jnp.where(active[:, None], new_logits[:, 0], logits)
            lengths = lengths + active.astype(jnp.int32)
            return (logits, lengths, keys, done, emitted, ring_k, ring_v), token

        (logits, lengths, keys, done, emitted, ring_k, ring_v), toks = jax.lax.scan(
            step, (logits, lengths, keys, done, emitted, ring_k, ring_v),
            jnp.arange(steps),
        )

        # ---- write-back: ring -> pool pages, once per chunk ----------
        # Page-aligned: per slot, shift the ring to page alignment
        # (first partial page merged from ctx so full-page writes
        # cannot clobber existing tokens), then DUS whole page blocks.
        # A lax.scan over each bucket's slots carries pk/pv in place
        # and keeps the program ~20 ops per slot instead of B*steps
        # token writes.  A runnable lane's first-page read is always in
        # range (its bucket's horizon covers ceil(len0/ps); at exact
        # page boundaries off0==0 and nothing needs preserving), and
        # non-runnable lanes (em==0) redirect every page to trash 0.
        n_back = steps // ps + 2  # pages a slot's chunk tokens can span
        W = n_back * ps
        p0 = jnp.minimum(len0, self.max_len - 1) // ps  # (B,) first page idx
        off0 = jnp.minimum(len0, self.max_len - 1) % ps

        tail0 = (0,) * len(tail)  # pool-rank index padding

        ctx_ks = ctx_k if multi else (ctx_k,)
        ctx_vs = ctx_v if multi else (ctx_v,)
        off_b = 0
        for b, (nb, _hb) in enumerate(buckets):
            ctx_k_b, ctx_v_b = ctx_ks[b], ctx_vs[b]
            base = off_b  # this bucket's first lane (static)

            def write_slot(carry, s, ctx_k_b=ctx_k_b, ctx_v_b=ctx_v_b,
                           base=base):
                pk, pv = carry
                g = base + s  # global lane index
                ring_k_s = jax.lax.dynamic_index_in_dim(
                    ring_k, g, axis=1, keepdims=False)  # (L, S, h, hd)
                ring_v_s = jax.lax.dynamic_index_in_dim(
                    ring_v, g, axis=1, keepdims=False)
                ctx_k_s = jax.lax.dynamic_index_in_dim(
                    ctx_k_b, s, axis=1, keepdims=False)  # (L, Cb, h, hd)
                ctx_v_s = jax.lax.dynamic_index_in_dim(
                    ctx_v_b, s, axis=1, keepdims=False)
                off = off0[g]
                first_k = jax.lax.dynamic_slice(
                    ctx_k_s, (0, p0[g] * ps, 0, 0), (L, ps, h, hd)
                )
                first_v = jax.lax.dynamic_slice(
                    ctx_v_s, (0, p0[g] * ps, 0, 0), (L, ps, h, hd)
                )
                aligned_k = jnp.zeros((L, W, h, hd), dtype)
                aligned_v = jnp.zeros((L, W, h, hd), dtype)
                aligned_k = jax.lax.dynamic_update_slice(
                    aligned_k, first_k, (0, 0, 0, 0))
                aligned_v = jax.lax.dynamic_update_slice(
                    aligned_v, first_v, (0, 0, 0, 0))
                aligned_k = jax.lax.dynamic_update_slice(
                    aligned_k, ring_k_s, (0, off, 0, 0))
                aligned_v = jax.lax.dynamic_update_slice(
                    aligned_v, ring_v_s, (0, off, 0, 0))
                table_s = jax.lax.dynamic_index_in_dim(
                    block_tables, g, axis=0, keepdims=False)
                em = jax.lax.dynamic_index_in_dim(
                    emitted, g, axis=0, keepdims=False)
                for j in range(n_back):
                    # page j holds accepted tokens iff its window starts
                    # before off0+emitted; inactive lanes (em==0) and
                    # pages past the accepted span are redirected to
                    # trash page 0
                    valid = (j * ps < off + em) & (em > 0)
                    page = jnp.where(
                        valid, jnp.take(table_s, p0[g] + j, mode="clip"), 0)
                    win_k = aligned_k[:, None, j * ps:(j + 1) * ps]  # (L,1,ps,h,hd)
                    win_v = aligned_v[:, None, j * ps:(j + 1) * ps]
                    if len(tail) == 1:  # flat pool: merge h x hd (contiguous)
                        win_k = win_k.reshape(L, 1, ps, -1)
                        win_v = win_v.reshape(L, 1, ps, -1)
                    pk = jax.lax.dynamic_update_slice(
                        pk, win_k, (0, page, 0) + tail0)
                    pv = jax.lax.dynamic_update_slice(
                        pv, win_v, (0, page, 0) + tail0)
                return (pk, pv), ()

            (pk, pv), _ = jax.lax.scan(write_slot, (pk, pv), jnp.arange(nb))
            off_b += nb

        if multi:
            toks_out = toks.T[inv_perm]
            (logits, lengths, keys, done, emitted) = (
                a[inv_perm] for a in (logits, lengths, keys, done, emitted)
            )
            return toks_out, pk, pv, logits, lengths, keys, done, emitted
        return toks.T, pk, pv, logits, lengths, keys, done, emitted

    def _chunk_fn_pool(
        self, steps, buckets, params, pk, pv, logits, lengths, block_tables,
        keys, done, emitted, max_new, temps, top_ks, eos_ids, perm,
        lora=None, adapter_idx=None,
    ):
        """Legacy chunk implementation (SELDON_TPU_CHUNK_IMPL=pool):
        per-step pool gather + per-slot DUS writes.  Kept selectable
        for A/B measurement and as the fallback while the ring path
        hardens; the pallas decode kernels only apply here.  The r6
        length-bucketed gather applies here too: lanes arrive permuted
        bucket-sorted and the per-step attention gathers each bucket's
        tables at its own static width (which must cover this chunk's
        growth — in-chunk tokens live in the pool, unlike the ring
        impl); writes use the full-width tables either way."""
        jax, jnp = self._jax, self._jnp
        params = self._materialize(params)

        multi = len(buckets) > 1
        if multi:
            inv_perm = jnp.argsort(perm)
            (logits, lengths, block_tables, keys, done, emitted, max_new,
             temps, top_ks, eos_ids) = (
                a[perm] for a in (
                    logits, lengths, block_tables, keys, done, emitted,
                    max_new, temps, top_ks, eos_ids)
            )
            if adapter_idx is not None:
                adapter_idx = adapter_idx[perm]
            split_tables = []
            off = 0
            for nb, hb in buckets:
                split_tables.append(block_tables[off:off + nb, :hb])
                off += nb
            attn_tables = tuple(split_tables)
        else:
            attn_tables = block_tables

        def step(carry, _):
            pk, pv, logits, lengths, keys, done, emitted = carry
            typed = jax.random.wrap_key_data(keys)
            split = jax.vmap(jax.random.split)(typed)
            step_keys = split[:, 1]
            token = self._sample_batch(logits, step_keys, temps, top_ks)
            active = ~done
            keys = jnp.where(
                active[:, None], jax.random.key_data(split[:, 0]), keys
            )
            token = jnp.where(active, token, eos_ids)
            emitted = emitted + active.astype(jnp.int32)
            done = done | (token == eos_ids) | (emitted >= max_new)
            positions = lengths[:, None]
            pk_pages, sk = kv_split(pk)
            pv_pages, sv = kv_split(pv)
            new_logits, nk, nv = self.module.apply(
                {"params": params}, token[:, None],
                jnp.minimum(positions, self.max_len - 1),
                pk_pages, pv_pages, attn_tables, lengths,
                lora=lora, adapter_idx=adapter_idx,
                kv_scales=kv_scales_arg(sk, sv),
            )
            pk, pv = self._write_kv(
                pk, pv, nk, nv, block_tables, lengths, active[:, None]
            )
            logits = jnp.where(active[:, None], new_logits[:, 0], logits)
            lengths = lengths + active.astype(jnp.int32)
            return (pk, pv, logits, lengths, keys, done, emitted), token

        (pk, pv, logits, lengths, keys, done, emitted), toks = jax.lax.scan(
            step, (pk, pv, logits, lengths, keys, done, emitted),
            None, length=steps,
        )
        if multi:
            toks_out = toks.T[inv_perm]
            (logits, lengths, keys, done, emitted) = (
                a[inv_perm] for a in (logits, lengths, keys, done, emitted)
            )
            return toks_out, pk, pv, logits, lengths, keys, done, emitted
        return toks.T, pk, pv, logits, lengths, keys, done, emitted

    def _draft_rollout_fn(self, params, windows, lens):
        """Greedy ``draft_k``-token rollout of the windowed draft model
        for every slot in ONE program.

        ``windows`` (slots, W) holds each context's last <=W tokens
        LEFT-aligned with ``lens`` (slots,) valid counts: for contexts
        that fit the window, token positions equal absolute positions —
        a draft sharing the target's architecture then reproduces the
        target's own argmaxes (the self-draft ceiling).  Longer
        contexts slide (drop-oldest), trading positional alignment for
        recency — a draft trained on sliding windows expects exactly
        that.  Draft quality only moves acceptance; the verify forward
        keeps output greedy-exact regardless.  Causal masking makes the
        zero-padding after ``lens`` invisible to positions < lens."""
        jax, jnp = self._jax, self._jnp
        W = self.draft_window
        S = windows.shape[0]

        def step(carry, _):
            win, ln = carry
            logits = self._draft_module.apply({"params": params}, win)
            tok = jnp.argmax(
                logits[jnp.arange(S), jnp.maximum(ln - 1, 0)], axis=-1
            ).astype(jnp.int32)
            full = ln >= W
            shifted = jnp.concatenate(
                [win[:, 1:], jnp.zeros((S, 1), win.dtype)], axis=1
            )
            win = jnp.where(full[:, None], shifted, win)
            pos = jnp.where(full, W - 1, ln)
            win = win.at[jnp.arange(S), pos].set(tok)
            ln = jnp.minimum(ln + 1, W)
            return (win, ln), tok

        (_, _), toks = jax.lax.scan(
            step, (windows, lens), None, length=self.draft_k
        )
        return toks.T  # (slots, draft_k)

    def _spec_chunk_fn(self, params, pk, pv, segs, n_drafts, active,
                       block_tables, lengths, lora=None, adapter_idx=None):
        """One verify forward for every active slot.

        ``segs[i]`` = [pending, d_1..d_k] (pads beyond ``n_drafts[i]``
        are never accepted).  The forward writes K/V for ALL k+1
        positions, but only ``accepted+1`` become visible — lengths
        advance by exactly that and rejected entries are overwritten by
        the next round (explicit lengths make rollback free, the same
        discipline as SpeculativeGenerator single-stream).
        """
        jax, jnp = self._jax, self._jnp
        params = self._materialize(params)
        L = self.draft_k + 1
        positions = lengths[:, None] + jnp.arange(L)[None, :]
        pk_pages, sk = kv_split(pk)
        pv_pages, sv = kv_split(pv)
        logits, nk, nv = self.module.apply(
            {"params": params}, segs,
            jnp.minimum(positions, self.max_len - 1),
            pk_pages, pv_pages, block_tables, lengths,
            lora=lora, adapter_idx=adapter_idx,
            kv_scales=kv_scales_arg(sk, sv),
        )
        greedy = jnp.argmax(logits, axis=-1)  # (S, L)
        match = (greedy[:, : L - 1] == segs[:, 1:]) & (
            jnp.arange(L - 1)[None, :] < n_drafts[:, None]
        )
        accepted = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
        idx = jnp.arange(L)[None, :]
        shifted = jnp.concatenate(
            [segs[:, 1:], jnp.zeros((segs.shape[0], 1), segs.dtype)], axis=1
        )
        bonus = jnp.take_along_axis(greedy, accepted[:, None], axis=1)
        out = jnp.where(idx < accepted[:, None], shifted,
                        jnp.where(idx == accepted[:, None], bonus, 0))
        counts = (accepted + 1) * active.astype(jnp.int32)
        pk, pv = self._write_kv(
            pk, pv, nk, nv, block_tables, lengths,
            jnp.broadcast_to(active[:, None], segs.shape),
        )
        lengths = lengths + counts
        return out, counts, pk, pv, lengths

    # ---- observability helpers -------------------------------------------

    def _gen_span(self, stream: _Stream, name: str, start_s: float,
                  duration_s: float, **tags: Any) -> None:
        """One gen.* lifecycle span for a stream, linked to the
        submitter's request span by the (trace_id=puid, parent_span_id)
        pair captured at submit — the decode loop runs on its own
        thread, so contextvar nesting cannot do it.  No-op (no tracer or
        untraced stream) costs one attribute read."""
        if not stream.trace_id:
            return
        from seldon_core_tpu.utils.tracing import record_span

        record_span(
            name, stream.trace_id, start_s, duration_s,
            parent_span_id=stream.parent_span_id,
            puid=stream.trace_id, req_id=stream.req_id, **tags,
        )

    def _gen_span_deferred(self, stream: _Stream, name: str, start_s: float,
                           duration_s: float, **tags: Any) -> None:
        """Queue a span from _lock-held code; step() flushes after the
        lock drops.  Caller must hold self._lock."""
        if stream.trace_id:
            self._pending_spans.append((stream, name, start_s, duration_s, tags))

    def _flush_spans(self) -> None:
        if not self._pending_spans:  # benign unlocked read: step() always re-runs
            return
        with self._lock:
            pending, self._pending_spans = self._pending_spans, []
        for stream, name, start_s, duration_s, tags in pending:
            self._gen_span(stream, name, start_s, duration_s, **tags)

    def _record_chunk(self, rec: Dict[str, Any]) -> None:
        # every per-chunk record names its decode lane (r18): the flight
        # recorder ring is the debug surface that answers "was the
        # Pallas kernel live when this chunk ran?" after the fact
        rec.setdefault("kernel_active", int(self._kernel_active))
        if self.recorder is not None:
            self.recorder.record(rec)
        self._feed_watchdog(float(rec.get("wall_ms", 0.0)), fault=False)

    # ---- black-box capture plane (r21) ----------------------------------

    def _note_breach_puids(self, records, path) -> None:
        """Flight-recorder dump hook: index every puid active in the
        breached window so its stream gets captured at termination —
        the dump is joinable to requests instead of staying an
        anonymous ring.  Runs outside the ring lock (and never takes
        the engine lock: recorder callbacks can fire from code paths
        that hold it)."""
        puids = {p for rec in records for p in rec.get("puids", ()) if p}
        if not puids:
            return
        with self._capture_lock:
            now = self._cost_clock()
            for p in puids:
                self._breach_puids[p] = now
            while len(self._breach_puids) > 1024:
                self._breach_puids.popitem(last=False)

    def capture_trigger(self, puid: str, error: Optional[BaseException]) -> Optional[str]:
        """The trigger matrix, evaluated once per terminating request:
        always-on-error > p99-breach membership > head sampling (every
        Nth completed request).  None = no capture."""
        if not self._capture_enabled:
            return None
        if error is not None:
            return "error"
        with self._capture_lock:
            if puid and self._breach_puids.pop(puid, None) is not None:
                return "breach"
            self._capture_seen += 1
            if self._capture_sample > 0 \
                    and self._capture_seen % self._capture_sample == 0:
                return "sample"
        return None

    def capture_request(self, stream: _Stream, *, puid: str, trigger: str,
                        status: str = "ok", reason: str = "",
                        tokens=None, extra: Optional[Dict[str, Any]] = None,
                        ) -> Optional[str]:
        """Assemble + store one request's black box: lifecycle phase
        terms, the recorder's wave slice for this puid, cost-ledger
        totals, the sampling recipe/seed, and the knob snapshot a
        replay rebuilds from.  Runs OUTSIDE the engine lock (callers
        sit past event.wait()); failures are contained — forensics
        never breaks serving."""
        if not self._capture_enabled:
            return None
        from seldon_core_tpu.utils import capture as _capture_mod

        try:
            waves = []
            if self.recorder is not None:
                waves = [r for r in self.recorder.snapshot()
                         if puid in r.get("puids", ())]
            extra = extra or {}
            cap = _capture_mod.RequestCapture(
                puid=puid,
                trace_id=stream.trace_id,
                status=status,
                reason=reason,
                trigger=trigger,
                seed=extra.get("request_seed"),
                max_new_tokens=stream.max_new,
                temperature=float(stream.temperature),
                top_k=int(stream.top_k),
                eos_id=stream.eos_id,
                adapter=stream.adapter,
                priority=int(stream.priority),
                deadline_remaining_ms=extra.get("deadline_remaining_ms"),
                rows=int(extra.get("rows", 1)),
                phases=_capture_mod.phase_terms(
                    stream.t_submit, stream.t_prefill_start,
                    stream.t_decode_start, stream.t_first_token,
                    stream.t_finish,
                ),
                waves=waves,
                cost={
                    "page_seconds": stream.cost_page_s,
                    "prefill_tokens": stream.cost_prefill_tokens,
                    "decode_tokens": stream.cost_decode_tokens,
                    "preemptions": stream.cost_preempts,
                    "restores": stream.cost_restores,
                    "adapter": stream.adapter or "base",
                },
                knobs=_capture_mod.knob_snapshot(),
                model=dict(extra.get("model") or {}),
                tags=dict(extra.get("tags") or {}),
                time=_capture_mod.now(),
                prompt=np.asarray(stream.prompt, np.int32).reshape(-1),
                tokens=(np.asarray(tokens, np.int32).reshape(-1)
                        if tokens is not None
                        else np.asarray(stream.tokens, np.int32)),
            )
            path = _capture_mod.default_store().put(cap)
        except Exception:  # noqa: BLE001 — forensics must not break serving
            logger.exception("request capture failed (puid=%s)", puid)
            return None
        if path is not None:
            with self._lock:
                self._counters["captures"] += 1
        return path

    def _feed_watchdog(self, wall_ms: float, fault: bool) -> None:
        """One per-wave observation into the health watchdog (r17):
        wall time (with the jitwatch sentinels' compile events exempting
        cold/compile waves from the ceiling), chunk faults, and
        allocator occupancy.  Runs OUTSIDE the engine lock except for
        one cheap occupancy read."""
        wd = self._watchdog
        if wd is None:
            return
        compiles = sum(s.compiles for s in self._sentinels.values())
        delta = compiles - self._wd_last_compiles
        self._wd_last_compiles = compiles
        with self._lock:
            used = self.num_pages - 1 - len(self._free_pages) - len(self._lru)
        total = max(1, self.num_pages - 1)
        wd.observe(
            wall_ms=wall_ms,
            compiled=delta > 0,
            fault=fault,
            pool_used_pct=100.0 * used / total,
            compiles_delta=delta,
        )

    def _quarantine_poisoned(self, runnable: List[_Stream]) -> List[_Stream]:
        """Post-chunk NaN/Inf screen on the served logits (r17): fault
        point ``paged.nan`` poisons ONE runnable lane first (chaos), the
        screen — one jitted ``isfinite`` reduction, (max_slots,) bools
        back — then retires every non-finite lane's stream with a 500
        ``NUMERIC_POISON`` and a ``quarantined`` count.  Wave-mates are
        untouched (lanes are arithmetically independent), so one sick
        stream never becomes a ``fail_all``.  Returns the quarantined
        streams; their slots/pages are already released.

        DECODE lane only: the speculative verify program returns argmax
        token ids — its logits never land in ``self._logits`` or reach
        the host at all, so there is nothing to screen there (and the
        ``paged.nan`` point, which lives here, does not fire on spec
        engines).  Documented in §11a / utils/faults.py."""
        jnp = self._jnp
        if runnable and _faults.enabled() and _faults.fire("paged.nan"):
            victim = min(runnable, key=lambda s: s.slot)
            self._logits = self._logits.at[victim.slot].set(jnp.nan)
            logger.warning(
                "injected paged.nan into slot %d (req %d)",
                victim.slot, victim.req_id,
            )
        if not self._nan_guard or not runnable:
            return []
        if self._isfinite_jit is None:
            self._isfinite_jit = self._jax.jit(
                lambda l: jnp.isfinite(l).all(axis=-1)
            )
        finite = np.asarray(self._isfinite_jit(self._logits))
        poisoned = [s for s in runnable if not finite[s.slot]]
        if not poisoned:
            return []
        with self._lock:
            for s in poisoned:
                self._counters["quarantined"] += 1
                self._fail_stream_locked(s, MicroserviceError(
                    f"stream req {s.req_id} quarantined: served logits "
                    f"went non-finite after {len(s.tokens)} tokens "
                    "(numeric poison contained to this stream; its "
                    "wave-mates are unaffected)",
                    status_code=500, reason="NUMERIC_POISON",
                ))
        logger.error(
            "NaN guard quarantined %d stream(s): %s",
            len(poisoned), [s.req_id for s in poisoned],
        )
        return poisoned

    def _profile_before_chunk(self) -> None:
        """SELDON_TPU_PROFILE_DIR hook: the first N chunk programs run
        inside one jax.profiler.trace for XLA-level inspection; profiler
        failures disable the hook, never decoding."""
        if self._profile_chunks_left <= 0 or self._profile_started:
            return
        try:
            self._jax.profiler.start_trace(self._profile_dir)
            self._profile_started = True
            logger.info(
                "profiling the next %d decode chunks to %s",
                self._profile_chunks_left, self._profile_dir,
            )
        except Exception:  # noqa: BLE001 — profiler failures disable the
            # hook, never decoding
            logger.exception("jax profiler start failed; hook disabled")
            self._profile_chunks_left = 0

    def _profile_after_chunk(self) -> None:
        if not self._profile_started:
            return
        self._profile_chunks_left -= 1
        if self._profile_chunks_left <= 0:
            try:
                self._jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — profiler failures never stop decoding
                logger.exception("jax profiler stop failed")
            self._profile_started = False

    # ---- host control -----------------------------------------------------

    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        eos_id: int = -1,
        seed: int = 0,
        draft_hint: Optional[np.ndarray] = None,
        stream_tokens: bool = False,
        trace_id: str = "",
        parent_span_id: Optional[str] = None,
        priority: int = 0,
        deadline: Optional[float] = None,
        kv_export: bool = False,
        kv_import: Optional[Dict[str, Any]] = None,
        adapter: Optional[str] = None,
        puid: str = "",
    ) -> _Stream:
        """Queue one prompt (1-D int array). Returns a stream handle whose
        ``event`` fires when ``result`` (``(max_new,)`` ids) is ready.

        ``draft_hint`` (speculative draft='oracle' only): the expected
        continuation, drafted verbatim — the acceptance-ceiling lane.

        ``trace_id``/``parent_span_id`` link this stream's ``gen.*``
        lifecycle spans into the submitter's trace (StreamingLM passes
        the request puid + its microservice span).  When omitted and a
        tracer is installed, the caller's active span is captured here —
        the decode loop runs on another thread, so the linkage must be
        pinned at submit time.

        ``priority`` (higher wins) orders admission, shedding and
        preemption; ``deadline`` is an absolute ``time.monotonic()``
        expiry — an already-expired submit fast-fails with 504, a
        queued stream whose budget dies is shed before it touches the
        device, and mid-decode expiry cancels the stream at the next
        chunk boundary.  Both default to the pre-SLO behaviour (every
        stream equal, no expiry), which keeps greedy decode bit-exact
        with the historical engine.

        ``kv_export`` (disaggregation, r15): the stream finishes at the
        END of prefill — its KV pages are read back into
        ``stream.kv_payload`` instead of decoding (``max_new_tokens``
        still sizes the request for admission but no decode runs).
        ``kv_import`` admits a prefill worker's payload: the pages are
        scatter-written (no prefill FLOPs) and decode starts from the
        imported last-token logits.  Prefer the :meth:`prefill_export`
        / :meth:`submit_prefilled` fronts, which validate payloads.

        ``adapter`` (multi-LoRA, r16) names the weight set this stream
        decodes with: a resident adapter pins its pool slot for the
        stream's lifetime, a cold one loads through the weight registry
        first (load -> pin -> serve -> unpin).  ``None`` is the base
        model — slot 0, the zero adapter, no lookup, no pin."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = len(prompt)
        if plen < 1:
            raise MicroserviceError(
                "empty prompt", status_code=400, reason="BAD_REQUEST"
            )
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise MicroserviceError(
                "max_new_tokens must be >= 1", status_code=400, reason="BAD_REQUEST"
            )
        if self.speculative is not None and temperature > 0:
            raise MicroserviceError(
                "the speculative engine is greedy-exact only: verification "
                "compares the model's argmax against drafts, which has no "
                "meaning under sampling — deploy without speculative (or "
                "send temperature=0) for this request",
                status_code=400, reason="BAD_REQUEST",
            )
        headroom = (self.draft_k + 1) if self.speculative is not None else 0
        bucket = next((b for b in self.prompt_buckets if b >= plen), None)
        if bucket is None or plen + max_new_tokens + headroom > self.max_len:
            raise MicroserviceError(
                f"prompt {plen} + max_new {max_new_tokens} exceeds max_len {self.max_len}",
                status_code=400, reason="SEQUENCE_TOO_LONG",
            )
        need = -(-(plen + max_new_tokens + headroom) // self.page_size)
        # capacity ceiling = the whole non-trash pool: LRU-cached prefix
        # pages are RECLAIMABLE (allocation evicts them on demand), so a
        # request is rejected only when it cannot fit even after every
        # cached page is reclaimed — a warm cache never shrinks the
        # admissible request size
        if need > self.num_pages - 1:
            raise MicroserviceError(
                f"request needs {need} pages but the pool holds {self.num_pages - 1}",
                status_code=400, reason="SEQUENCE_TOO_LONG",
            )
        import time as _time

        if deadline is not None and _time.monotonic() >= deadline:
            # fast-fail before queueing: a spent budget must not burn a
            # queue slot, an admission wave, or a single decode step
            raise deadline_exceeded("paged-engine submit")
        # adapter resolution BEFORE the queue lock: a cold adapter pays
        # registry load + device install here, on the submitting thread
        # — never inside an engine wave.  The returned slot carries a
        # temp pin that transfers onto the stream below (or rolls back
        # if admission itself rejects).  CHEAP admission checks run
        # first: an overload burst that is about to shed (or a closed
        # engine) must not thrash warm adapters out of the pool with
        # cold loads for requests that never serve.
        adapter = adapter or None
        if adapter is not None:
            with self._lock:
                if self._closed:
                    raise MicroserviceError(
                        "engine closed", status_code=503,
                        reason="SHUTTING_DOWN",
                    )
                if self.max_queue and len(self._queue) >= self.max_queue:
                    # may raise 503 SHED for this request (or make room
                    # by shedding a lower-priority victim — the same
                    # policy _submit_pinned re-checks after the load)
                    self._shed_for_admission_locked(int(priority))
        adapter_slot = (
            self._acquire_adapter_slot(adapter) if adapter is not None else 0
        )
        try:
            return self._submit_pinned(
                prompt, max_new_tokens, temperature, top_k, eos_id, seed,
                draft_hint, stream_tokens, trace_id, parent_span_id,
                priority, deadline, kv_export, kv_import, adapter,
                adapter_slot, puid,
            )
        except BaseException:
            if adapter_slot:
                with self._lock:
                    self._drop_temp_pin_locked(adapter_slot)
                    self._unpin_adapter_slot_locked(adapter_slot)
            raise

    def _submit_pinned(
        self, prompt, max_new_tokens, temperature, top_k, eos_id, seed,
        draft_hint, stream_tokens, trace_id, parent_span_id,
        priority, deadline, kv_export, kv_import, adapter, adapter_slot,
        puid="",
    ) -> _Stream:
        import queue as _queue
        import time as _time

        with self._lock:
            if self._closed:
                raise MicroserviceError(
                    "engine closed", status_code=503, reason="SHUTTING_DOWN"
                )
            if self.max_queue and len(self._queue) >= self.max_queue:
                self._shed_for_admission_locked(int(priority))
            stream = _Stream(
                self._next_id, prompt, max_new_tokens,
                float(temperature), int(top_k), int(eos_id), int(seed),
            )
            stream.priority = int(priority)
            stream.deadline = float(deadline) if deadline is not None else None
            stream.kv_export = bool(kv_export)
            stream.kv_import = kv_import
            stream.adapter = adapter
            stream.adapter_slot = int(adapter_slot)
            if adapter_slot:
                # the temp pin becomes the stream's pin — refcount
                # unchanged, attribution moves (the audit counts both)
                stream.adapter_pinned = True
                self._drop_temp_pin_locked(adapter_slot)
                self._adapter_requests[adapter] = (
                    self._adapter_requests.get(adapter, 0) + 1
                )
            if draft_hint is not None:
                stream.draft_hint = np.asarray(draft_hint, np.int32).reshape(-1)
            if stream_tokens:
                stream.token_queue = _queue.Queue()
            self._next_id += 1
            # always stamped (one time() call): TTFT is measured as
            # t_first_token - t_submit by the bench gate and the
            # profile tool, tracer installed or not
            stream.t_submit = _time.time()
            stream.queue_depth_at_submit = len(self._queue)
            # puid linkage is independent of tracing: wave records and
            # capture containers must join to the request even when no
            # tracer is installed (trace_id remains the fallback key)
            stream.puid = str(puid or trace_id or "")
            from seldon_core_tpu.utils import tracing as _tracing

            if _tracing.get_tracer() is not None:  # one global read when off
                enclosing = _tracing.current_span()
                stream.trace_id = trace_id or (
                    enclosing.trace_id if enclosing is not None
                    else f"gen-{stream.req_id}"
                )
                stream.parent_span_id = parent_span_id or (
                    enclosing.span_id if enclosing is not None else None
                )
            self._queue.append(stream)
            self._queued.add(stream)
        return stream

    def submit_views(self, views, **kwargs) -> List["_Stream"]:
        """Batched submission front for the zero-copy lane: N token
        buffer views (1-D int32 — ``np.frombuffer`` windows over the
        ingress byte buffers, no python-list or proto round-trip) are
        decoded zero-copy and admitted in one pass.  Each stream keeps
        EXACTLY :meth:`submit`'s semantics — validation, queue-bound
        shedding, priority admission, deadline fast-fail — so the SLO
        path (r10) sees no behaviour change; the batching only amortises
        the per-request python marshalling.

        ``kwargs`` apply to every view (per-request settings: call
        :meth:`submit` directly).  Admission is all-or-nothing: when a
        later view's admission raises (SEQUENCE_TOO_LONG, deadline
        fast-fail, SHED), every stream already admitted by this call is
        cancelled before the error surfaces — otherwise they would
        decode tokens nobody holds a handle to.
        """
        from seldon_core_tpu.codec.bufview import BufferView

        prompts = []
        for v in views:
            arr = v.array() if isinstance(v, BufferView) else np.asarray(v)
            if arr.dtype != np.int32:
                arr = arr.astype(np.int32, copy=False)
            prompts.append(arr.reshape(-1))
        admitted: List[_Stream] = []
        try:
            for p in prompts:
                admitted.append(self.submit(p, **kwargs))
        except BaseException:
            for s in admitted:
                try:
                    self.cancel(s)
                except Exception:  # noqa: BLE001 — rollback is best-effort;
                    # the admission error below is the one the caller acts on
                    logger.exception("submit_views rollback cancel failed")
            raise
        return admitted

    # ---- multi-LoRA adapter pool: slots, pins, LRU reclaim (r16) ----------

    def _unpin_adapter_slot_locked(self, slot: int) -> None:
        """Drop one pin on a pool slot; the last pin parks the slot on
        the adapter LRU (still resident — reclaimed only when a cold
        load needs it, the capacity-not-cost discipline).  Caller holds
        ``_lock``."""
        r = int(self._adapter_ref[slot]) - 1
        self._adapter_ref[slot] = max(r, 0)
        if r <= 0 and slot in self._adapter_names:
            self._adapter_lru[slot] = self._adapter_names[slot]

    def _release_adapter_locked(self, stream: _Stream) -> None:
        """Terminal-path unpin (finish / fail / export / queued-cancel):
        exactly once per stream — the ``adapter_pinned`` flag guards
        the multiple terminal paths that can race to retire one
        stream.  Caller holds ``_lock``."""
        if not stream.adapter_pinned:
            return
        stream.adapter_pinned = False
        self._unpin_adapter_slot_locked(stream.adapter_slot)

    def _install_adapter(self, name: str, params: Dict[str, Any]) -> int:
        """Place one adapter's factors into a pool slot (called under
        ``_adapter_io_lock``, NOT holding ``_lock``): take a free slot
        or reclaim the LRU refcount-0 one; every slot pinned is a clean
        503 — adapter capacity is a serving error, never a crash.  The
        returned slot carries ONE pin (a temp pin the caller transfers
        or drops)."""
        victim: Optional[str] = None
        with self._lock:
            if self._adapter_free:
                slot = self._adapter_free.pop()
            elif self._adapter_lru:
                slot, victim = self._adapter_lru.popitem(last=False)
                del self._adapter_table[victim]
                self._adapter_names.pop(slot, None)
                self._counters["adapter_evictions"] += 1
            else:
                raise MicroserviceError(
                    f"adapter pool exhausted: all {self.max_adapters} "
                    "slots pinned by live streams",
                    status_code=503, reason="ADAPTERS_EXHAUSTED",
                )
            self._adapter_installing.add(slot)
        if victim is not None and victim in self._adapter_reg_pinned:
            # the evicted adapter's registry pin drops: its host copy
            # becomes reclaimable registry capacity (weight-page LRU)
            self._adapter_reg_pinned.discard(victim)
            self._registry.release(victim)
        # device install outside _lock: .at[].set builds new factor
        # buffers the NEXT wave reads — shapes unchanged, nothing
        # recompiles, and no wave is in flight on this slot (it was
        # free or refcount-0).  Shape/target validation happens BEFORE
        # any write, so a wrong-rank or partial adapter is a clean 400
        # with the slot returned untouched.
        try:
            self._lora.install(slot, params)
        except ValueError as exc:
            with self._lock:
                self._adapter_installing.discard(slot)
                self._adapter_free.append(slot)
            raise MicroserviceError(
                f"adapter {name!r} does not fit this engine's factor "
                f"pool: {exc}",
                status_code=400, reason="ADAPTER_INCOMPATIBLE",
            ) from exc
        except BaseException:
            with self._lock:
                self._adapter_installing.discard(slot)
                self._adapter_free.append(slot)
            raise
        with self._lock:
            self._adapter_installing.discard(slot)
            self._adapter_table[name] = slot
            self._adapter_names[slot] = name
            self._adapter_ref[slot] = 1
            self._adapter_temp_pins[slot] = (
                self._adapter_temp_pins.get(slot, 0) + 1
            )
            self._counters["adapter_loads"] += 1
        return slot

    def _acquire_adapter_slot(self, name: str) -> int:
        """Resolve ``name`` to a pinned pool slot — the cold-admission
        path of the issue's load -> pin -> serve -> unpin: a resident
        adapter is a hit (pin bumps), a cold one loads through the
        weight registry (budget-priced) and installs.  The pin is
        recorded as a temp pin until :meth:`submit` attaches it to the
        stream, so the allocator audit balances at every instant."""
        if self._lora is None:
            raise MicroserviceError(
                "this engine serves no adapters (max_adapters=0 / "
                "SELDON_TPU_MAX_ADAPTERS unset)",
                status_code=400, reason="ADAPTERS_DISABLED",
            )

        # resident fast path NEVER touches the io lock: check-and-pin
        # is atomic under _lock (a pinned slot can't be reclaimed —
        # eviction requires refcount 0), so warm submits must not
        # serialize behind another adapter's slow cold load
        with self._lock:
            slot = self._pin_resident_adapter_locked(name)
            if slot is not None:
                return slot
        with self._adapter_io_lock:
            with self._lock:
                # re-check: a concurrent cold load may have installed it
                slot = self._pin_resident_adapter_locked(name)
                if slot is not None:
                    return slot
                self._counters["adapter_misses"] += 1
            if self._registry is None or not self._registry.known(name):
                raise MicroserviceError(
                    f"unknown adapter {name!r}: not resident and not "
                    "registered in the weight registry",
                    status_code=404, reason="ADAPTER_UNKNOWN",
                )
            params = self._registry.acquire(name)
            try:
                slot = self._install_adapter(name, params)
            except BaseException:
                self._registry.release(name)
                raise
            # the registry pin is held while the adapter stays resident
            # in THIS pool (released on pool eviction / unload / close)
            self._adapter_reg_pinned.add(name)
            return slot

    def _pin_resident_adapter_locked(self, name: str) -> Optional[int]:
        """Hit path of adapter resolution: pin ``name``'s slot (ref +
        temp pin) if it is resident, else None.  Caller holds
        ``_lock``."""
        slot = self._adapter_table.get(name)
        if slot is None:
            return None
        self._counters["adapter_hits"] += 1
        self._adapter_ref[slot] += 1
        self._adapter_temp_pins[slot] = (
            self._adapter_temp_pins.get(slot, 0) + 1
        )
        self._adapter_lru.pop(slot, None)
        return slot

    def _drop_temp_pin_locked(self, slot: int) -> None:
        n = self._adapter_temp_pins.get(slot, 0) - 1
        if n > 0:
            self._adapter_temp_pins[slot] = n
        else:
            self._adapter_temp_pins.pop(slot, None)

    def load_adapter(self, name: str, params: Optional[Dict[str, Any]] = None) -> int:
        """Hot-load ``name`` into the pool WITHOUT serving from it
        (warm-up / tools): direct ``params`` install, or a registry
        pull when omitted.  Returns the slot; the adapter parks
        refcount-0 on the LRU (resident, reclaimable)."""
        if params is not None:
            if self._lora is None:
                raise MicroserviceError(
                    "this engine serves no adapters (max_adapters=0)",
                    status_code=400, reason="ADAPTERS_DISABLED",
                )
            with self._adapter_io_lock:
                with self._lock:
                    slot = self._adapter_table.get(name)
                    if slot is not None:
                        return slot
                slot = self._install_adapter(name, params)
                with self._lock:
                    self._drop_temp_pin_locked(slot)
                    self._unpin_adapter_slot_locked(slot)
                return slot
        slot = self._acquire_adapter_slot(name)
        with self._lock:
            self._drop_temp_pin_locked(slot)
            self._unpin_adapter_slot_locked(slot)
        return slot

    def unload_adapter(self, name: str) -> None:
        """Explicitly evict a resident adapter (rolling re-deploys).
        Pinned adapters refuse with 409 — live streams must never have
        their factors swapped mid-decode."""
        with self._adapter_io_lock:
            with self._lock:
                slot = self._adapter_table.get(name)
                if slot is None:
                    return
                if int(self._adapter_ref[slot]) > 0:
                    raise MicroserviceError(
                        f"adapter {name!r} is pinned by live streams",
                        status_code=409, reason="ADAPTER_IN_USE",
                    )
                del self._adapter_table[name]
                self._adapter_names.pop(slot, None)
                self._adapter_lru.pop(slot, None)
                self._adapter_free.append(slot)
            if name in self._adapter_reg_pinned:
                self._adapter_reg_pinned.discard(name)
                self._registry.release(name)

    def adapter_stats(self) -> Dict[str, Any]:
        """The ``GET /debug/weights`` per-engine payload: residency,
        per-slot pins, and the pool's per-shard HBM price."""
        with self._lock:
            resident = [
                {
                    "name": name,
                    "slot": slot,
                    "refcount": int(self._adapter_ref[slot]),
                    "cached": slot in self._adapter_lru,
                }
                for name, slot in sorted(self._adapter_table.items())
            ]
            return {
                "enabled": self._lora is not None,
                "max_adapters": self.max_adapters,
                "rank": self._lora.rank if self._lora is not None else 0,
                "pool_bytes": (
                    self._lora.hbm_bytes(self.tp_degree)
                    if self._lora is not None else 0
                ),
                "resident": resident,
                "requests": dict(self._adapter_requests),
            }

    # ---- refcounted page allocator + prefix cache (r9) --------------------

    def _allocatable_locked(self) -> int:
        """Pages available right now: the free list plus the LRU-cached
        set (refcount-0 prefix pages are reclaimable on demand, so
        capacity accounting must count them as available)."""
        return len(self._free_pages) + len(self._lru)

    def _evict_cached_locked(self) -> None:
        """Reclaim the least-recently-used cached page: unregister it
        from the prefix index and return it to the free list.  With the
        KV tier on (r22) the page is STAGED for host demotion first:
        its KV stays valid until the next pool-writing device call, and
        every such call is preceded by a _tier_flush that gathers the
        staged pages host-side — demote instead of discard, off the
        allocation hot path."""
        page, entry = self._lru.popitem(last=False)  # oldest first
        self._prefix_index.pop(entry.key, None)
        self._page_entry.pop(page, None)
        if self._kv_tier is not None:
            self._tier_pending.append(
                (entry.key, entry.parent, entry.tokens, page)
            )
        self._free_pages.append(page)
        self._counters["prefix_evictions"] += 1

    def _alloc_locked(self, n: int) -> Optional[List[int]]:
        """Take ``n`` fresh pages (refcount 1 each), evicting LRU-cached
        pages under pressure.  Stack-discipline deque: O(1) per page.

        Fault point ``paged.alloc`` (utils/faults.py): an armed
        injection reports exhaustion exactly as a genuinely full pool
        would, driving the caller's stall/evict/rollback machinery."""
        if _faults.fire("paged.alloc"):
            return None
        if self._allocatable_locked() < n:
            return None
        while len(self._free_pages) < n:
            self._evict_cached_locked()
        out = [self._free_pages.popleft() for _ in range(n)]
        for p in out:
            self._page_ref[p] = 1
        return out

    def _free_locked(self, pages: List[int]) -> None:
        """Release one stream's mapping of ``pages``.  A page whose
        refcount drops to zero either parks on the LRU cached set (it
        is a registered prefix page — its KV stays valid and a later
        admission can remap it) or returns to the free list.  Reversed
        iteration inserts a stream's DEEPEST prefix pages into the LRU
        first (oldest), so under pressure leaves evict before the
        parents their chain lookups walk through."""
        for p in reversed(pages):
            r = int(self._page_ref[p]) - 1
            self._page_ref[p] = max(r, 0)
            if r > 0:
                continue
            entry = self._page_entry.get(p)
            if entry is not None and self._prefix_cache_enabled:
                self._lru[p] = entry  # most-recent end
            else:
                if entry is not None:  # registered but caching disabled
                    self._prefix_index.pop(entry.key, None)
                    self._page_entry.pop(p, None)
                self._free_pages.append(p)

    # ---- per-request cost ledger (r20) ------------------------------------

    def _cost_touch_locked(self, stream: _Stream) -> None:
        """Accrue the stream's KV occupancy integral up to NOW: called
        immediately before every change to ``len(stream.pages)`` (grow,
        free, admit) so ``cost_page_s`` is exact at page-count
        granularity — pages-held x seconds, stamped at the boundaries
        where the count changes.  No-op when the telemetry plane is
        off (no clock reads on the =0 lane)."""
        if not self._telemetry_enabled:
            return
        now = self._cost_clock()
        if stream.cost_t:
            stream.cost_page_s += (now - stream.cost_t) * len(stream.pages)
        stream.cost_t = now

    def _cost_close_locked(self, stream: _Stream) -> None:
        """Fold one terminating stream's ledger into the engine totals
        and the per-adapter split — exactly once per stream (the
        ``cost_closed`` guard covers paths that can race a second
        termination, e.g. a migrated-out stream whose peer import later
        fails back through ``fail_stream``).  Accruing totals and the
        split from the SAME event is what makes the per-adapter
        counters sum to the fleet totals exactly."""
        if not self._telemetry_enabled or stream.cost_closed:
            return
        self._cost_touch_locked(stream)
        stream.cost_t = 0.0
        stream.cost_closed = True
        self._counters["cost_page_seconds"] += stream.cost_page_s
        self._counters["cost_prefill_tokens"] += stream.cost_prefill_tokens
        self._counters["cost_decode_tokens"] += stream.cost_decode_tokens
        entry = self._cost_by_adapter.setdefault(
            stream.adapter or "base",
            {"page_seconds": 0.0, "prefill_tokens": 0,
             "decode_tokens": 0, "streams": 0},
        )
        entry["page_seconds"] += stream.cost_page_s
        entry["prefill_tokens"] += stream.cost_prefill_tokens
        entry["decode_tokens"] += stream.cost_decode_tokens
        entry["streams"] += 1

    def _prefix_root_for(self, adapter: Optional[str]) -> int:
        """Chain root per weight set (r16): adapter-selected prefill
        writes DIFFERENT KV than the base model for the same tokens, so
        each adapter chains off its own root — two tenants sharing a
        system prompt share pages only within one adapter.  The base
        model keeps the historical root (cache keys unchanged when
        adapters are off)."""
        if not adapter:
            return _PREFIX_ROOT
        return prefix_chain_key(_PREFIX_ROOT, (adapter,))

    def _match_prefix_locked(
        self, prompt: np.ndarray, root: int
    ) -> List[_CachedPrefix]:
        """Longest cached prefix of FULL prompt pages, walked root →
        leaf through the chain-keyed index in O(pages).  The last
        prompt page is always private — even when the prompt is an
        exact page multiple — so the suffix prefill always has at least
        one token to produce the next-token logits from.  Colliding
        keys verify parent AND token equality before sharing: a hash
        collision (including an adapter root colliding with another's)
        degrades to a miss, never to foreign KV.  No LRU touching
        here: the caller pops every matched refcount-0 page off the
        LRU when it maps them (and its rollback re-inserts deepest
        first), so the leaves-evict-before-parents ordering is
        maintained entirely by insertion discipline."""
        if not self._prefix_cache_enabled:
            return []
        ps = self.page_size
        n_full = (len(prompt) - 1) // ps
        matched: List[_CachedPrefix] = []
        parent = root
        for i in range(n_full):
            toks = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            key = prefix_chain_key(parent, toks)
            entry = self._prefix_index.get(key)
            if entry is None or entry.parent != parent or entry.tokens != toks:
                break
            matched.append(entry)
            parent = key
        return matched

    def _register_prefix_locked(self, stream: _Stream) -> None:
        """Publish a prefilled stream's full prompt pages into the
        prefix index (called once the prefill device call owning their
        KV has been issued — later programs read the pool through the
        threaded pages_k/pages_v arrays, so the data dependency orders
        any shared read after this write).  Pages whose key is already
        registered stay private: either they ARE the registered page
        (matched at admission), a concurrent identical prompt got there
        first (its page is canonical, ours frees normally), or the key
        collides with different tokens (never share unverified
        content — and stop, since lookups cannot walk past a collision
        either)."""
        if not self._prefix_cache_enabled or stream.slot is None:
            return
        if self._slots[stream.slot] is not stream:
            # the stream lost its slot between admission and here
            # (fail_all/close from another thread, cancel retirement):
            # its pages are already released — nothing to publish
            return
        ps = self.page_size
        prompt = stream.prompt
        n_full = (len(prompt) - 1) // ps
        parent = self._prefix_root_for(stream.adapter)
        for i in range(n_full):
            toks = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            key = prefix_chain_key(parent, toks)
            entry = self._prefix_index.get(key)
            if entry is None:
                page = stream.pages[i]
                if page not in self._page_entry:
                    e = _CachedPrefix(key, page, toks, parent)
                    self._prefix_index[key] = e
                    self._page_entry[page] = e
                    if self._kv_tier is not None:
                        # one residency per key (r22): a freshly
                        # prefilled copy in HBM supersedes any demoted
                        # container still parked in the tier
                        self._kv_tier.discard(key)
            elif entry.parent != parent or entry.tokens != toks:
                break  # collision: descendants are unreachable anyway
            parent = key

    def _check_invariants_locked(self) -> None:
        """SELDON_TPU_PAGED_DEBUG=1 audit (chunk boundaries): the
        non-trash pages partition into free ∪ cached ∪ mapped, refcounts
        equal the number of live block tables holding each page, and
        every LRU entry is consistent with the prefix index."""
        problems: List[str] = []
        free = list(self._free_pages)
        free_set = set(free)
        if len(free_set) != len(free):
            problems.append("duplicate pages on the free list")
        cached = set(self._lru)
        mapped: Dict[int, int] = {}
        for s in self._slots:
            if s is None:
                continue
            for i, p in enumerate(s.pages):
                mapped[p] = mapped.get(p, 0) + 1
                if int(self._block_tables[s.slot, i]) != p:
                    problems.append(
                        f"slot {s.slot} block table col {i} != stream page {p}"
                    )
        for a, b, name in (
            (free_set, cached, "free∩cached"),
            (free_set, set(mapped), "free∩mapped"),
            (cached, set(mapped), "cached∩mapped"),
        ):
            if a & b:
                problems.append(f"pages simultaneously {name}: {sorted(a & b)}")
        every = free_set | cached | set(mapped)
        want = set(range(1, self.num_pages))
        if every != want:
            problems.append(
                f"leaked pages {sorted(want - every)} / phantom {sorted(every - want)}"
            )
        for p in want:
            if int(self._page_ref[p]) != mapped.get(p, 0):
                problems.append(
                    f"page {p} refcount {int(self._page_ref[p])} != "
                    f"{mapped.get(p, 0)} live mappings"
                )
        for p, entry in self._lru.items():
            if entry.page != p or self._prefix_index.get(entry.key) is not entry \
                    or self._page_entry.get(p) is not entry:
                problems.append(f"LRU entry for page {p} inconsistent with index")
        problems.extend(self._adapter_problems_locked())
        if self._kv_tier is not None:
            # tier partition (r22): the tier's own level/accounting
            # invariants, plus no chain key resident in HBM AND the
            # tier at once (register discards, promote pops — a key
            # appearing in both means one of those paths was skipped)
            problems.extend(self._kv_tier.audit())
            dual = self._kv_tier.keys() & set(self._prefix_index)
            if dual:
                problems.append(
                    "prefix keys resident in HBM AND the KV tier: "
                    f"{sorted(dual)}"
                )
        if problems:
            raise RuntimeError(
                "paged allocator invariant violation: " + "; ".join(problems)
            )

    def _adapter_problems_locked(self) -> List[str]:
        """The SELDON_TPU_PAGED_DEBUG audit extended to WEIGHT slots
        (r16): non-zero pool slots partition into free ∪ resident,
        per-slot refcounts equal live-stream pins plus in-submit temp
        pins, and the adapter LRU holds exactly the refcount-0
        residents."""
        if self._lora is None:
            return []
        problems: List[str] = []
        free = set(self._adapter_free)
        named = set(self._adapter_names)
        installing = set(self._adapter_installing)
        if free & named:
            problems.append(
                f"adapter slots simultaneously free and named: {sorted(free & named)}"
            )
        if (free | named) & installing:
            problems.append(
                "adapter slots simultaneously installing and free/named: "
                f"{sorted((free | named) & installing)}"
            )
        if free | named | installing != set(range(1, self.max_adapters + 1)):
            problems.append("adapter slots leaked or phantom")
        pins: Dict[int, int] = dict(self._adapter_temp_pins)
        for s in list(self._queue) + [s for s in self._slots if s is not None]:
            if s.adapter_pinned:
                pins[s.adapter_slot] = pins.get(s.adapter_slot, 0) + 1
        for slot in range(1, self.max_adapters + 1):
            want = pins.get(slot, 0)
            if int(self._adapter_ref[slot]) != want:
                problems.append(
                    f"adapter slot {slot} refcount "
                    f"{int(self._adapter_ref[slot])} != {want} pins"
                )
            cached = slot in self._adapter_lru
            if cached and int(self._adapter_ref[slot]) > 0:
                problems.append(f"adapter slot {slot} cached while pinned")
            if slot in named and not cached and int(self._adapter_ref[slot]) == 0:
                problems.append(
                    f"adapter slot {slot} resident, unpinned, but not on the LRU"
                )
        for slot, name in self._adapter_lru.items():
            if self._adapter_table.get(name) != slot:
                problems.append(
                    f"adapter LRU entry {name!r}@{slot} inconsistent with table"
                )
        return problems

    # ---- SLO lifecycle: shed / expire / preempt (r10) ---------------------

    def _remove_queued_locked(self, stream: _Stream) -> None:
        if stream in self._queued:
            self._queue.remove(stream)
            self._queued.discard(stream)

    def _fail_stream_locked(self, stream: _Stream, exc: Exception) -> None:
        """Error-terminate one stream (shed, expiry, contained chunk
        fault): slot and pages released, waiter unblocked with ``exc``
        — the SLO/chaos twin of ``_finish_locked``, which delivers a
        result.  Works for queued (no slot) and in-slot streams."""
        slot = stream.slot
        stream.error = exc
        if stream.trace_id:
            import time as _time

            self._gen_span_deferred(
                stream, "gen.finish", _time.time(), 0.0,
                slot=slot, tokens=len(stream.tokens), error=True,
                reason=getattr(exc, "reason", type(exc).__name__),
            )
        if slot is not None and self._slots[slot] is stream:
            self._slots[slot] = None
            self._lengths[slot] = 0
        self._cost_close_locked(stream)
        self._tier_putback_locked(stream)
        if stream.pages:
            self._free_locked(stream.pages)
            stream.pages = []
        stream.slot = None
        self._release_adapter_locked(stream)
        if stream.token_queue is not None:
            stream.token_queue.put(None)
        stream.event.set()

    def _shed_expired_queued_locked(self) -> int:
        """Drop queued streams whose budget is already spent — they
        must never reach the device (the scheduler's 'skip expired'
        rule).  Returns the number dropped."""
        if not self._queue:
            return 0
        import time as _time

        now = _time.monotonic()
        victims = [
            s for s in self._queue
            if s.deadline is not None and now >= s.deadline
        ]
        for s in victims:
            self._remove_queued_locked(s)
            self._counters["expired"] += 1
            self._fail_stream_locked(
                s, deadline_exceeded(f"paged-engine queue (req {s.req_id})")
            )
        return len(victims)

    def _shed_for_admission_locked(self, priority: int) -> None:
        """Make room for an arriving submit when the bounded queue is
        full.  Policy (docs/operations.md runbook): already-expired
        queued streams are dropped first; if the queue is still full the
        lowest-priority queued stream sheds — but only when it ranks
        strictly BELOW the newcomer (ties shed the newcomer: arrival
        order breaks ties, or admission would livelock under uniform
        load).  Shedding raises/errors 503 ``SHED`` so callers can
        retry elsewhere."""
        self._shed_expired_queued_locked()
        if len(self._queue) < self.max_queue:
            return
        # lowest class first; within a class the NEWEST sheds (oldest
        # are closest to service — dropping them maximises wasted wait)
        victim = min(self._queue, key=lambda s: (s.priority, -s.req_id))
        self._counters["shed"] += 1
        if victim.priority >= priority:
            raise MicroserviceError(
                f"queue full ({self.max_queue}) and every queued stream has "
                f"priority >= {priority}: request shed under overload",
                status_code=503, reason="SHED",
            )
        self._remove_queued_locked(victim)
        self._fail_stream_locked(
            victim,
            MicroserviceError(
                f"shed under overload: queue full ({self.max_queue}) and a "
                f"priority-{priority} request arrived "
                f"(this stream: priority {victim.priority})",
                status_code=503, reason="SHED",
            ),
        )

    def _preempt_victim_locked(self, stream: _Stream) -> Optional[_Stream]:
        """The in-flight stream a pages-starved ``stream`` may evict: a
        strictly lower-priority one (least priority, then least decoded
        progress, ties to the youngest).  None = no preemption — equal
        classes never preempt each other, so the default (all priority
        0) engine behaves exactly as before."""
        candidates = [
            s for s in self._slots
            if s is not None and s.priority < stream.priority
        ]
        if not candidates:
            return None
        return min(
            candidates, key=lambda s: (s.priority, len(s.tokens), -s.req_id)
        )

    def _try_admit_locked(self, slot: int, stream: _Stream) -> bool:
        """One admission attempt for ``stream`` into ``slot``: prefix
        match + refcount bumps + fresh alloc; False rolls every bump
        back (deepest page re-parked first, preserving the leaves-
        evict-first LRU discipline)."""
        plen = len(stream.prompt)
        # KV imports never map shared prefix pages: the payload's
        # scatter would write INTO pages other streams read (same
        # values, but shared pages are read-only by contract) — they
        # allocate fresh pages and re-register afterwards instead
        matched = (
            [] if stream.kv_import is not None
            else self._match_prefix_locked(
                stream.prompt, self._prefix_root_for(stream.adapter)
            )
        )
        for e in matched:
            if int(self._page_ref[e.page]) == 0:
                self._lru.pop(e.page, None)
            self._page_ref[e.page] += 1
        # hierarchical KV tier (r22): continue the chain walk PAST the
        # HBM match into the host/disk tier — every popped container is
        # a full prompt page whose KV re-enters through the donated
        # scatter (tier_promote below) instead of re-running prefill.
        # Popped entries are owned by this admission: alloc failure
        # puts them back, stream death before the scatter puts them
        # back (_tier_putback_locked), success re-registers them in the
        # prefix index after the suffix prefill.
        tier_hits: List[Tuple[int, int, Tuple[int, ...], Dict[str, Any],
                              bytes, str]] = []
        tier = self._kv_tier
        if (
            tier is not None and stream.kv_import is None
            and self._prefix_cache_enabled
        ):
            from seldon_core_tpu.codec.tensor import PayloadError

            ps = self.page_size
            n_full = (plen - 1) // ps
            parent = (
                matched[-1].key if matched
                else self._prefix_root_for(stream.adapter)
            )
            for i in range(len(matched), n_full):
                toks = tuple(
                    int(t) for t in stream.prompt[i * ps:(i + 1) * ps]
                )
                key = prefix_chain_key(parent, toks)
                try:
                    got = tier.pop(key, parent, toks)
                except PayloadError as exc:
                    # corrupted container: the tier already dropped the
                    # entry — this page (and the chain below it)
                    # re-prefills, nothing scatters
                    logger.warning(
                        "KV tier container for chain key %d rejected: %s",
                        key, exc,
                    )
                    got = None
                if got is None:
                    # the remaining uncached full pages re-prefill:
                    # they are the hit-rate denominator's other half
                    self._counters["kv_tier_misses"] += n_full - i
                    break
                payload, blob, level = got
                tier_hits.append((key, parent, toks, payload, blob, level))
                parent = key
        # migration imports (r17) arrive with decoded tokens whose KV
        # pages must be placed alongside the prompt's at admission
        extra = 0
        if stream.kv_import is not None:
            toks = stream.kv_import.get("tokens")
            extra = 0 if toks is None else len(toks)
        fresh = self._alloc_locked(
            -(-(plen + extra) // self.page_size) - len(matched)
        )
        if fresh is None:
            for key, parent_k, toks, _payload, blob, _level in reversed(
                tier_hits
            ):
                tier.put(key, parent_k, toks, blob)
            for e in reversed(matched):
                self._page_ref[e.page] -= 1
                if int(self._page_ref[e.page]) == 0:
                    self._lru[e.page] = e
            return False
        self._remove_queued_locked(stream)
        stream.slot = slot
        stream.pages = [e.page for e in matched] + fresh
        if self._telemetry_enabled:
            # occupancy integral starts (or restarts) here: the stream
            # now holds pages; every later page-count change touches
            # first, so the integral is exact at change boundaries
            stream.cost_t = self._cost_clock()
        stream.cached_len = len(matched) * self.page_size
        # chunked-prefill cursor: prefill resumes past the cached
        # prefix; slices advance it to plen (monolithic prefill jumps
        # there in one wave)
        stream.prefilled = stream.cached_len
        if self._prefix_cache_enabled:
            if matched:
                self._counters["prefix_hits"] += 1
                self._counters["prefix_tokens_saved"] += stream.cached_len
            else:
                self._counters["prefix_misses"] += 1
        if tier_hits:
            # the tier chain scatters into the first fresh pages (they
            # continue the matched chain in block-table order); the
            # cached/prefilled cursors jump past them so prefill covers
            # only the genuinely-uncached suffix.  Prefix counters
            # above deliberately kept HBM-only semantics (cached_len at
            # this point == len(matched) * page_size).
            n_t = len(tier_hits)
            stream.tier_promote = {"pages": fresh[:n_t], "entries": tier_hits}
            stream.cached_len = (len(matched) + n_t) * self.page_size
            stream.prefilled = stream.cached_len
            self._counters["kv_tier_promotions"] += 1
            for _key, _par, _toks, _payload, blob, level in tier_hits:
                self._counters[
                    "kv_tier_host_hits" if level == "host"
                    else "kv_tier_disk_hits"
                ] += 1
                self._counters["kv_tier_bytes_promoted"] += len(blob)
        if stream.preempted:
            # a preemptively-evicted stream coming back: its decoded
            # progress re-derives deterministically and any still-cached
            # prompt pages just re-matched above — the restore half of
            # evict/restore
            stream.preempted = False
            stream.cost_restores += 1
            self._counters["restored"] += 1
        self._slots[slot] = stream
        row = np.zeros((self.pages_per_stream,), np.int32)
        row[: len(stream.pages)] = stream.pages
        self._block_tables[slot] = row
        self._lengths[slot] = plen
        # the lane's adapter slot id: every engine program gathers this
        # lane's low-rank factors by it (0 = the zero adapter)
        self._adapter_slots[slot] = stream.adapter_slot
        return True

    def _preempt_locked(self, stream: _Stream) -> Optional[int]:
        """Preempt the best victim for ``stream`` (strictly lower
        priority only); returns the freed slot, or None when nothing is
        preemptible.  The victim goes through the ordinary evict path:
        re-queued at the head, progress re-derived deterministically on
        restore, prompt pages usually surviving in the prefix cache."""
        victim = self._preempt_victim_locked(stream)
        if victim is None:
            return None
        slot = victim.slot
        self._counters["preempted"] += 1
        victim.preempted = True
        victim.cost_preempts += 1
        self._evict_locked(victim)
        return slot

    def _admit_locked(self) -> List[Tuple[_Stream, int]]:
        """Move queued streams into slots; returns admissions.

        Order: expired queued streams are dropped first (they must not
        cost an admission wave), then the highest-priority queued
        stream takes the next slot — FIFO within a class (``max``
        returns the first maximal element, and evict/restore re-queues
        at the head), which is EXACTLY the historical FIFO when every
        priority is 0.  An admission that cannot get a SLOT (all busy)
        or PAGES (pool exhausted) may preempt a strictly lower-priority
        in-flight stream through the ordinary evict path, so long
        low-priority prompts can never starve interactive traffic;
        equal classes never preempt each other, keeping the default
        engine bit-exact with its pre-SLO behaviour.

        Prefix-cache lookup happens inside ``_try_admit_locked``: the
        longest chain of cached full prompt pages maps into the new
        stream's block table with ``refcount += 1`` and only the
        remainder allocates fresh pages — prefill then runs over the
        uncached suffix alone."""
        admitted: List[Tuple[_Stream, int]] = []
        self._shed_expired_queued_locked()
        free_slots: Deque[int] = deque(
            slot for slot in range(self.max_slots)
            if self._slots[slot] is None
        )
        while self._queue:
            stream = max(self._queue, key=lambda s: s.priority)
            if not free_slots:
                # slot starvation: a higher-priority arrival may evict
                # a lower-priority in-flight stream for its slot
                slot = self._preempt_locked(stream)
                if slot is None:
                    break
                free_slots.append(slot)
                continue  # re-select: the preemptor still ranks first
            if self._try_admit_locked(free_slots[0], stream):
                admitted.append((stream, len(stream.prompt)))
                free_slots.popleft()
                continue
            # pages exhausted with a slot in hand: preempt for pages,
            # else stop the whole wave (don't let a short request
            # starve the head — the historical FIFO discipline)
            slot = self._preempt_locked(stream)
            if slot is None:
                break
            free_slots.append(slot)
        return admitted

    def _prefill_streams(
        self, streams: List[_Stream]
    ) -> Tuple[List[_Stream], int, float]:
        """Monolithic prefill wave (chunk budget OFF — the historical
        path): every admitted stream's full uncached suffix runs in
        this one wave.  Returns ``(completed streams, prompt tokens
        computed, wall seconds)`` — the same contract as the chunked
        slice runner, so both step paths share one completion tail."""
        return self._run_prefill_slices([
            (s, s.prefilled, len(s.prompt) - s.prefilled) for s in streams
        ])

    def _plan_prefill_slices_locked(
        self, prefilling: List[_Stream], budget: int
    ) -> List[Tuple[_Stream, int, int]]:
        """Token-budget slice plan for this wave (the Sarathi-Serve
        rule): pending prefills ordered priority-first then FIFO, each
        taking up to the remaining budget, floored to a page boundary
        unless the slice finishes the prompt — the next slice's
        "cached" length must stay page-aligned for the suffix program's
        shifted write table.  KV imports cost no budget: their pages
        arrive computed, the wave only places them.  Caller holds
        ``_lock``; execution happens later, outside it."""
        slices: List[Tuple[_Stream, int, int]] = []
        left = int(budget)
        ps = self.page_size
        for s in sorted(prefilling, key=lambda s: (-s.priority, s.req_id)):
            need = len(s.prompt) - s.prefilled
            if s.kv_import is not None:
                slices.append((s, s.prefilled, need))
                continue
            if left < ps:
                continue  # cannot make page-aligned progress this wave
            n = min(left, need)
            if n < need:
                n = (n // ps) * ps
            if n <= 0:
                continue
            slices.append((s, s.prefilled, n))
            left -= n
        return slices

    def _run_prefill_slices(
        self, slices: List[Tuple[_Stream, int, int]]
    ) -> Tuple[List[_Stream], int, float]:
        """Execute one wave's prefill work: ``(stream, start, n)``
        slices, ``start`` page-aligned (it is the stream's ``prefilled``
        cursor).  KV imports scatter first (no FLOPs), then per-bucket
        grouped device calls — the classic from-zero program for whole
        prompts (byte-identical to the pre-chunking engine, so the
        budget-off lane keeps its compiled shapes) and the r9
        cached-suffix program for everything mid-prompt: a chunk slice
        IS a suffix prefill whose "cached" prefix is the pages earlier
        slices already wrote.  Returns ``(completed streams, prompt
        tokens computed, wall seconds)``; kv_export streams resolve
        with their handoff payload instead of entering decode."""
        if not slices:
            return [], 0, 0.0
        # KV tier (r22): staged demotions must gather before this
        # wave's prefill programs can overwrite their pages
        self._tier_flush()
        import time as _time

        t_start = _time.perf_counter()
        t_admit = _time.time()
        for stream, start, _n in slices:
            if not stream.t_prefill_start:
                stream.t_prefill_start = t_admit  # queue-wait term ends
            # queue-wait is the irreducible tail term (§10a): one span
            # per stream, emitted on its FIRST slice
            if stream.trace_id and start == stream.cached_len:
                self._gen_span(
                    stream, "gen.queued", stream.t_submit or t_admit,
                    max(0.0, t_admit - stream.t_submit)
                    if stream.t_submit else 0.0,
                    slot=stream.slot,
                    queue_depth=stream.queue_depth_at_submit,
                )
        completed: List[_Stream] = []
        tokens = 0
        calls = 0
        # group by the bucket covering what actually prefills THIS
        # wave: the full prompt only for an uncached whole-prompt
        # slice; cache hits and mid-prompt chunk slices pay a
        # suffix-sized program
        plain: Dict[int, List[Tuple[_Stream, int, int]]] = {}
        cached: Dict[int, List[Tuple[_Stream, int, int]]] = {}
        for stream, start, n in slices:
            if stream.kv_import is not None:
                self._import_kv_stream(stream)
                completed.append(stream)
                continue
            bucket = next(b for b in self.prompt_buckets if b >= n)
            target = (
                plain if start == 0 and n == len(stream.prompt) else cached
            )
            target.setdefault(bucket, []).append((stream, start, n))
            tokens += n
        for bucket, group in plain.items():
            completed.extend(
                self._prefill_group(bucket, group, use_cache=False)
            )
            calls += 1
        for bucket, group in cached.items():
            completed.extend(
                self._prefill_group(bucket, group, use_cache=True)
            )
            calls += 1
        wall = _time.perf_counter() - t_start
        with self._lock:
            if calls:
                self._counters["prefill_wall_s"] += wall
                self._counters["prefill_tokens"] += tokens
                self._counters["prefill_chunks"] += calls
            if self._prefix_cache_enabled:
                # publish full prompt pages only once the WHOLE
                # prompt's KV is resident (the chain registration walks
                # every page); the device calls that wrote them have
                # been issued, and any later shared read is ordered
                # after them by the threaded pool arrays
                for stream in completed:
                    self._register_prefix_locked(stream)
        exports = [s for s in completed if s.kv_export]
        if exports:
            self._export_streams(exports)
            completed = [s for s in completed if not s.kv_export]
        return completed, tokens, wall

    def _prefill_group(
        self, bucket: int, group: List[Tuple[_Stream, int, int]],
        use_cache: bool,
    ) -> List[_Stream]:
        """One batched prefill device call for ``group`` slices (all
        same bucket; ``use_cache`` selects the suffix program attending
        over already-resident pages — shared prefix pages and pages
        earlier chunk slices wrote — vs the classic from-zero program,
        which stays byte-identical to the pre-cache engine so the
        cache-off lane keeps its compiled shapes).  Returns the streams
        whose prompt is now FULLY prefilled: their decode state
        (logits, rng keys, speculative pending) installs here;
        mid-prompt slices only advance the ``prefilled`` cursor."""
        import time as _time

        jnp = self._jnp
        t_group = _time.time()
        k = 1
        while k < len(group):
            k *= 2
        ps = self.page_size
        # multi-LoRA trailing args: per-row adapter slots (pad rows 0 —
        # the zero adapter, deltas exactly 0.0 into the trash page)
        lora_args: Tuple[Any, ...] = ()
        if self._lora is not None:
            adapter_rows = np.zeros((k,), np.int32)
            for i, (stream, _start, _n) in enumerate(group):
                adapter_rows[i] = stream.adapter_slot
            lora_args = (self._lora.device_args(), jnp.asarray(adapter_rows))
        if use_cache:
            rp = self._pages_pow2(
                max(1, max(start // ps for _s, start, _n in group))
            )
            wp = -(-bucket // ps)
            key3 = (bucket, k, rp)
            if key3 not in self._prefill_cached_jit:
                self._prefill_cached_jit[key3] = self._build_prefill_cached(
                    bucket, k, rp
                )
            padded = np.zeros((k, bucket), np.int32)
            true_lens = np.ones((k,), np.int32)  # pad rows: 1 token -> trash
            cached_lens = np.zeros((k,), np.int32)
            read_rows = np.zeros((k, rp), np.int32)
            write_rows = np.zeros((k, wp), np.int32)
            for i, (stream, start, n) in enumerate(group):
                padded[i, :n] = stream.prompt[start : start + n]
                true_lens[i] = n
                cached_lens[i] = start
                read_rows[i] = self._block_tables[stream.slot, :rp]
                # shifted write table: slice block j lands in the page
                # AFTER the resident prefix (start is page-aligned, so
                # every write starts at offset 0 — the from_zero fast
                # path)
                cp = start // ps
                row = self._block_tables[stream.slot, cp : cp + wp]
                write_rows[i, : len(row)] = row
            last, pk_out, pv_out = self._prefill_cached_jit[key3](
                self.params, *self._kv_args(),
                jnp.asarray(padded), jnp.asarray(true_lens),
                jnp.asarray(cached_lens), jnp.asarray(read_rows),
                jnp.asarray(write_rows), *lora_args,
            )
            self._store_kv(pk_out, pv_out)
        else:
            key2 = (bucket, k)
            if key2 not in self._prefill_jit:
                self._prefill_jit[key2] = self._build_prefill(bucket, k)
            # slice block rows to the bucket's page span: prefill reads
            # no cache (lengths 0) and writes at most `bucket` tokens,
            # so gathering the full worst-case table would be pure
            # wasted HBM traffic (same reasoning as _pages_horizon)
            pages_h = self._pages_pow2(-(-bucket // self.page_size))
            padded = np.zeros((k, bucket), np.int32)
            true_lens = np.ones((k,), np.int32)  # pad rows: 1 token -> trash
            block_rows = np.zeros((k, pages_h), np.int32)
            for i, (stream, _start, n) in enumerate(group):
                padded[i, :n] = stream.prompt
                true_lens[i] = n
                block_rows[i] = self._block_tables[stream.slot, :pages_h]
            last, pk_out, pv_out = self._prefill_jit[key2](
                self.params, *self._kv_args(),
                jnp.asarray(padded), jnp.asarray(true_lens),
                jnp.asarray(block_rows), *lora_args,
            )
            self._store_kv(pk_out, pv_out)
        finals: List[Tuple[int, _Stream]] = []
        for i, (stream, start, n) in enumerate(group):
            stream.prefilled = start + n
            stream.cost_prefill_tokens += n
            if stream.prefilled >= len(stream.prompt):
                finals.append((i, stream))
        if not finals:
            return []
        g = len(finals)
        # batched tail: per-stream .at[].set / key() calls are tiny
        # device dispatches, and ~3 per stream serialised through a
        # relayed dispatch stream measured as a large share of
        # admission wall time at 16 joiners.  Three dispatches total
        # instead: one fixed-shape key derivation, two scatters.
        slots = jnp.asarray(
            np.array([s.slot for _i, s in finals], np.int32)
        )
        # deterministic per submit(seed=...): same seed -> same
        # sample path (per-request variation is the component
        # layer's job, as in GenerativeLM's puid/counter folding).
        # Seeds fold into [0, 2^63) — same key for any practical
        # seed (component layers derive seeds well below 2^63)
        seeds = np.zeros((self.max_slots,), np.uint64)
        for j, (_i, stream) in enumerate(finals):
            seeds[j] = stream.seed % (1 << 63)
        all_keys = self._derive_keys(jnp.asarray(seeds))
        self._keys = self._keys.at[slots].set(all_keys[:g])
        last_f = last[jnp.asarray(np.array([i for i, _s in finals], np.int32))]
        self._logits = self._logits.at[slots].set(last_f)
        if self.speculative is not None:
            # host decides the next greedy token between verify
            # rounds — ONE blocking readback for the whole group
            pending = np.asarray(jnp.argmax(last_f, axis=-1))
            for j, (_i, stream) in enumerate(finals):
                stream.pending = int(pending[j])
        exports = [
            (j, stream) for j, (_i, stream) in enumerate(finals)
            if stream.kv_export
        ]
        if exports:
            # the handoff payload carries the last-token logits so the
            # decode worker starts sampling without a forward of its own
            last_np = np.asarray(last_f)
            for j, stream in exports:
                stream.kv_payload = {
                    "last_logits": last_np[j].astype(np.float32, copy=False)
                }
        t_done = _time.time()
        out: List[_Stream] = []
        for _i, stream in finals:
            stream.t_decode_start = t_done
            if stream.trace_id:
                # the group prefills in ONE device call, so every
                # member's span carries the group wall (tagged with
                # the group size so a reader knows it is shared)
                self._gen_span(
                    stream, "gen.prefill", t_group, t_done - t_group,
                    slot=stream.slot, bucket=bucket,
                    prompt_len=len(stream.prompt),
                    cached_tokens=stream.cached_len,
                    pages_held=len(stream.pages),
                    group_size=len(group),
                )
            out.append(stream)
        return out

    # ---- disaggregated prefill/decode: KV-page handoff (r15) --------------

    def _build_import_kv(self, P: int):
        """Donated KV-page scatter for one imported payload: the pages
        arrive computed (the prefill worker ran the FLOPs), this
        program only places them — in AND out pool shardings pinned by
        ``_tp_jit`` so a TP-sharded pool round-trips without a
        resharding copy."""

        jax = self._jax

        def imp(params, pk, pv, k, v, pages):
            del params  # present only for _tp_jit's argument convention
            # int8 pools arrive as (pages, scales) bundles with k/v
            # bundled the same way — the scale table indexes its page
            # axis identically, so ONE tree-mapped scatter places both
            place = lambda pool, val: pool.at[:, pages].set(val)  # noqa: E731
            return jax.tree.map(place, pk, k), jax.tree.map(place, pv, v)

        return self._tp_jit(imp, n_rep_in=3, out_spec=("pool", "pool"))

    def _import_kv_stream(self, stream: _Stream) -> None:
        """Scatter an imported prefill's pages into this pool and
        install the stream's decode state — the decode half of the
        disaggregated handoff.  Afterwards the stream is
        indistinguishable from one that prefilled locally (same rng
        keys, same logits, same page discipline), which is what makes
        disaggregated decode bit-exact with unified serving."""
        # KV tier (r22): the scatter below writes the pool — staged
        # demotions gather first (no-op on the direct call path, where
        # _run_prefill_slices already flushed)
        self._tier_flush()
        import time as _time

        jnp = self._jnp
        payload = stream.kv_import
        t0 = _time.time()
        plen = len(stream.prompt)
        # migration imports (r17) also carry the decoded-token pages:
        # the peer resumes at the exact next token, so the scatter
        # places prompt AND generated KV in one donated call
        mig_tokens = payload.get("tokens")
        extra = 0 if mig_tokens is None else len(mig_tokens)
        total = plen + extra
        P = -(-total // self.page_size)
        pages = np.asarray(stream.pages[:P], np.int32)
        fn = self._import_kv_jit.get(P)
        if fn is None:
            fn = self._import_kv_jit[P] = self._build_import_kv(P)
        k = jnp.asarray(np.asarray(payload["k"]), self._pool_dtype)
        v = jnp.asarray(np.asarray(payload["v"]), self._pool_dtype)
        if self._kv_int8:
            k = (k, jnp.asarray(np.asarray(payload["k_scales"]), jnp.float32))
            v = (v, jnp.asarray(np.asarray(payload["v_scales"]), jnp.float32))
        pk_out, pv_out = fn(
            self.params, *self._kv_args(), k, v,
            jnp.asarray(pages),
        )
        self._store_kv(pk_out, pv_out)
        last = np.asarray(
            payload["last_logits"], np.float32
        ).reshape(-1)
        slot = stream.slot
        self._logits = self._logits.at[slot].set(jnp.asarray(last))
        key_data = payload.get("key_data")
        if key_data is not None and np.asarray(key_data).size:
            # mid-decode migration: the source's post-chunk rng state
            # resumes the SAME sample path (a re-derived key would fork
            # a sampled stream at the migration boundary)
            self._keys = self._keys.at[slot].set(
                jnp.asarray(np.asarray(key_data, np.uint32))
            )
        else:
            seeds = np.zeros((self.max_slots,), np.uint64)
            seeds[0] = stream.seed % (1 << 63)
            self._keys = self._keys.at[slot].set(
                self._derive_keys(jnp.asarray(seeds))[0]
            )
        if self.speculative is not None:
            pending = payload.get("pending")
            stream.pending = (
                int(pending) if pending is not None else int(np.argmax(last))
            )
        stream.prefilled = plen
        migration = bool(payload.get("migration"))
        if extra:
            stream.tokens = [int(t) for t in np.asarray(mig_tokens).reshape(-1)]
        if migration:
            stream.streamed = int(payload.get("streamed") or 0)
        stream.t_decode_start = _time.time()
        with self._lock:
            if extra:
                # decode resumes mid-sequence: lengths must count the
                # generated tokens' KV the scatter just placed
                self._lengths[slot] = total
            stream.kv_import = None  # payload consumed: free the host copy
            stream.kv_imported = True
            self._counters["migrated_in" if migration else "kv_imports"] += 1
        if stream.trace_id:
            self._gen_span(
                stream, "gen.prefill", t0, stream.t_decode_start - t0,
                slot=slot, bucket=0, prompt_len=plen,
                cached_tokens=0, pages_held=len(stream.pages),
                group_size=1, imported=True, migrated=migration,
            )

    # ---- hierarchical KV tier (r22) ---------------------------------------

    def _tier_flush(self) -> None:
        """Gather every staged demotion host-side into SRT1 containers
        and hand them to the tier.  MUST run (and does — see the call
        sites) before any device call that writes the KV pool: a staged
        page sits on the free list with its KV still valid, which holds
        exactly until the next pool-writing program runs.  Called
        OUTSIDE the engine lock (device readback + container packing);
        single-stepper discipline makes that safe — the one step()
        thread is the only allocator of the staged pages' next life.

        Known (accepted) window: a chain demoted THIS wave cannot
        promote on a same-wave re-admission — admission ran before the
        flush, so the keys were neither in HBM nor yet in the tier.  It
        promotes from the next wave on."""
        tier = self._kv_tier
        if tier is None:
            return
        with self._lock:
            if not self._tier_pending:
                return
            pending, self._tier_pending = self._tier_pending, []
            # a key re-registered since staging is HBM-resident again —
            # demoting it too would put one key at two levels
            pending = [e for e in pending if e[0] not in self._prefix_index]
        if not pending:
            return
        from seldon_core_tpu.codec.bufview import pack_kv_handoff

        jnp = self._jnp
        idx = jnp.asarray(np.asarray([e[3] for e in pending], np.int32))
        k = np.asarray(self.pages_k[:, idx])
        v = np.asarray(self.pages_v[:, idx])
        ks = vs = None
        if self._kv_int8:
            # int8 pages demote NATIVELY with their sibling per-page
            # scales — the promote scatter re-places both, exactly as
            # the disaggregation wire does
            ks = np.asarray(self.scales_k[:, idx])
            vs = np.asarray(self.scales_v[:, idx])
        layout = "flat" if self._pool_flat else "split"
        demoted = 0
        bytes_demoted = 0
        evicted = 0
        for i, (key, parent, toks, _page) in enumerate(pending):
            payload = {
                "prompt": np.asarray(toks, np.int32),
                # containers carry last_logits for the disaggregation
                # handoff; a demoted page has none — promotion never
                # reads the frame
                "last_logits": np.zeros((1,), np.float32),
                "k": k[:, i:i + 1],
                "v": v[:, i:i + 1],
                "page_size": self.page_size,
                "layout": layout,
            }
            if ks is not None:
                payload["k_scales"] = ks[:, i:i + 1]
                payload["v_scales"] = vs[:, i:i + 1]
            blob = pack_kv_handoff(payload)
            evicted += tier.put(key, parent, toks, blob)
            demoted += 1
            bytes_demoted += len(blob)
        with self._lock:
            self._counters["kv_tier_demotions"] += demoted
            self._counters["kv_tier_bytes_demoted"] += bytes_demoted
            self._counters["kv_tier_evictions"] += evicted

    def _tier_promote_ready(self) -> None:
        """Scatter every freshly-admitted stream's promoted tier chain
        into its fresh HBM pages — one donated ``.at[:, pages].set``
        per stream through the SAME compiled import program the
        disaggregation lane uses (no new program shapes on the off
        lane, transfer cost instead of prefill FLOPs).  Runs right
        after the admission wave, before any prefill slice or decode
        chunk touches the streams."""
        if self._kv_tier is None:
            return
        # demotions staged by this admission wave's allocations gather
        # BEFORE the promote scatter below can overwrite their pages
        self._tier_flush()
        with self._lock:
            todo: List[Tuple[_Stream, Dict[str, Any]]] = []
            for s in self._slots:
                if s is not None and s.tier_promote is not None:
                    todo.append((s, s.tier_promote))
                    s.tier_promote = None
        if not todo:
            return
        jnp = self._jnp
        for _stream, tp in todo:
            entries = tp["entries"]
            pages = np.asarray(tp["pages"], np.int32)
            k = np.concatenate(
                [np.asarray(e[3]["k"]) for e in entries], axis=1
            )
            v = np.concatenate(
                [np.asarray(e[3]["v"]) for e in entries], axis=1
            )
            P = len(pages)
            fn = self._import_kv_jit.get(P)
            if fn is None:
                fn = self._import_kv_jit[P] = self._build_import_kv(P)
            kd = jnp.asarray(k, self._pool_dtype)
            vd = jnp.asarray(v, self._pool_dtype)
            if self._kv_int8:
                kd = (kd, jnp.asarray(np.concatenate(
                    [np.asarray(e[3]["k_scales"]) for e in entries], axis=1
                ), jnp.float32))
                vd = (vd, jnp.asarray(np.concatenate(
                    [np.asarray(e[3]["v_scales"]) for e in entries], axis=1
                ), jnp.float32))
            pk_out, pv_out = fn(
                self.params, *self._kv_args(), kd, vd, jnp.asarray(pages)
            )
            self._store_kv(pk_out, pv_out)

    def _tier_putback_locked(self, stream: _Stream) -> None:
        """Return an UNCONSUMED promotion's containers to the tier — a
        stream that dies between admission and its promote scatter
        (cancel, shed, fail_all, eviction) owns popped tier entries
        whose KV never landed anywhere; dropping them would silently
        lose demoted state the next admission could have used."""
        tp = stream.tier_promote
        if tp is None:
            return
        stream.tier_promote = None
        tier = self._kv_tier
        if tier is None:
            return
        for key, parent, toks, _payload, blob, _level in reversed(
            tp["entries"]
        ):
            tier.put(key, parent, toks, blob)

    def _export_streams(self, streams: List[_Stream]) -> None:
        """Resolve kv_export streams with their KV-page handoff payload
        (prompt, per-page K/V, last-token logits): one device gather +
        readback per stream, then the pages release through the normal
        free path — the full prompt pages were registered in the prefix
        index just before, so a prefill worker keeps a warm prefix
        cache across exports."""
        import time as _time

        jnp = self._jnp
        for stream in streams:
            P = -(-len(stream.prompt) // self.page_size)
            idx = jnp.asarray(np.asarray(stream.pages[:P], np.int32))
            k = np.asarray(self.pages_k[:, idx])
            v = np.asarray(self.pages_v[:, idx])
            payload = {
                "prompt": np.asarray(stream.prompt, np.int32),
                "k": k,
                "v": v,
                "last_logits": np.asarray(
                    (stream.kv_payload or {}).get("last_logits"), np.float32
                ).reshape(-1),
                "page_size": self.page_size,
                "layout": "flat" if self._pool_flat else "split",
            }
            if self._kv_int8:
                # int8 pages travel NATIVELY — the per-page scales ride
                # as sibling frames, so the wire carries half the bytes
                # and the importer never dequantises
                payload["k_scales"] = np.asarray(self.scales_k[:, idx])
                payload["v_scales"] = np.asarray(self.scales_v[:, idx])
            with self._lock:
                stream.kv_payload = payload
                slot = stream.slot
                if slot is not None and self._slots[slot] is stream:
                    self._slots[slot] = None
                    self._lengths[slot] = 0
                self._cost_close_locked(stream)
                if stream.pages:
                    self._free_locked(stream.pages)
                    stream.pages = []
                stream.slot = None
                self._release_adapter_locked(stream)
                self._counters["kv_exports"] += 1
                self._counters["completed"] += 1
                if stream.trace_id:
                    self._gen_span_deferred(
                        stream, "gen.finish", _time.time(), 0.0,
                        slot=slot, tokens=0, kv_export=True,
                    )
                stream.event.set()

    def prefill_export(
        self,
        prompt: np.ndarray,
        *,
        seed: int = 0,
        priority: int = 0,
        deadline: Optional[float] = None,
        drive: bool = True,
        adapter: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Synchronous prefill-only front — the prefill WORKER's one
        call in disaggregated serving: admit ``prompt``, run its
        (possibly chunked) prefill, and return the KV-page handoff
        payload for :meth:`submit_prefilled` on a decode engine.
        ``drive=False`` when another thread owns the step loop (the
        single-stepper invariant); the default drives inline."""
        stream = self.submit(
            np.asarray(prompt), max_new_tokens=1, seed=seed,
            priority=priority, deadline=deadline, kv_export=True,
            adapter=adapter,
        )
        if drive:
            while not stream.event.is_set() and self.has_work():
                self.step()
        stream.event.wait()
        if stream.error is not None:
            raise stream.error
        return stream.kv_payload

    def submit_prefilled(self, payload: Dict[str, Any], **kw) -> _Stream:
        """Admit a prefill worker's KV-page payload for decode (the
        receiving half of disaggregation); ``kw`` forwards to
        :meth:`submit` (priority/deadline/streaming — the r10 SLO
        machinery applies unchanged).  The payload is validated against
        this engine's pool geometry first, because a scatter of
        mismatched bytes would serve garbage rather than raise."""
        prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
        k = np.asarray(payload["k"])
        v = np.asarray(payload["v"])
        last = np.asarray(payload["last_logits"], np.float32).reshape(-1)
        ps = int(payload.get("page_size", self.page_size))
        if ps != self.page_size:
            raise MicroserviceError(
                f"KV payload page_size {ps} != engine page_size "
                f"{self.page_size}: prefill and decode workers must share "
                "one pool configuration",
                status_code=400, reason="KV_LAYOUT_MISMATCH",
            )
        P = -(-len(prompt) // self.page_size)
        want = (self.module.num_layers, P) + tuple(self.pages_k.shape[2:])
        for name, arr in (("k", k), ("v", v)):
            if tuple(arr.shape) != want:
                raise MicroserviceError(
                    f"KV payload {name} shape {tuple(arr.shape)} does not "
                    f"fit this engine's pool geometry {want} (layers, "
                    "prompt pages, page tail)",
                    status_code=400, reason="KV_LAYOUT_MISMATCH",
                )
            if arr.dtype != np.dtype(self._pool_dtype):
                raise MicroserviceError(
                    f"KV payload {name} dtype {arr.dtype} != pool dtype "
                    f"{np.dtype(self._pool_dtype)}",
                    status_code=400, reason="KV_LAYOUT_MISMATCH",
                )
        if last.shape[0] != self.vocab_size:
            raise MicroserviceError(
                f"KV payload last_logits carries {last.shape[0]} entries, "
                f"engine vocab is {self.vocab_size}",
                status_code=400, reason="KV_LAYOUT_MISMATCH",
            )
        kv = {"k": k, "v": v, "last_logits": last}
        if self._kv_int8:
            kv["k_scales"], kv["v_scales"] = self._validate_kv_scales(
                payload, P, "KV payload"
            )
        return self.submit(prompt, kv_import=kv, **kw)

    def _validate_kv_scales(self, payload: Dict[str, Any], P: int,
                            kind: str) -> Tuple[np.ndarray, np.ndarray]:
        """Validate an int8 container's per-page scale frames against
        this engine's pool geometry — an int8 page without its scale
        would serve garbage rather than raise, same reasoning as the
        shape checks above."""
        out = []
        for name in ("k_scales", "v_scales"):
            arr = payload.get(name)
            if arr is None:
                raise MicroserviceError(
                    f"{kind} carries int8 pages but no {name} frame — "
                    "int8 KV containers must carry one f32 scale per "
                    "page per k/v",
                    status_code=400, reason="KV_LAYOUT_MISMATCH",
                )
            arr = np.asarray(arr)
            want = (self.module.num_layers, P)
            if tuple(arr.shape) != want:
                raise MicroserviceError(
                    f"{kind} {name} shape {tuple(arr.shape)} does not fit "
                    f"the scale-table geometry {want} (layers, pages)",
                    status_code=400, reason="KV_LAYOUT_MISMATCH",
                )
            if arr.dtype != np.float32:
                raise MicroserviceError(
                    f"{kind} {name} dtype {arr.dtype} != float32",
                    status_code=400, reason="KV_LAYOUT_MISMATCH",
                )
            out.append(arr)
        return out[0], out[1]

    # ---- live stream migration (r17) --------------------------------------

    def migrate_export(
        self, streams: Optional[Sequence[_Stream]] = None
    ) -> List[Tuple[Dict[str, Any], _Stream]]:
        """Snapshot mid-decode streams for live migration to a peer
        engine: KV pages (prompt AND generated-token pages), the decode
        cursor (token ids so far), per-slot RNG state, sampling params,
        remaining deadline, priority, adapter name and the streaming
        cursor — everything :meth:`migrate_import` needs to resume at
        the exact next token, greedy bit-exact with the uninterrupted
        run.  Call with the step loop quiesced (no chunk in flight —
        the same precondition as :meth:`drain`).

        Exports the given ``streams`` (default: every in-slot stream)
        that are EXPORTABLE: fully prefilled, not a disaggregation
        export, not mid-import, and not on a speculative engine (the
        verify pipeline's pending-draft state stays host-local; spec
        streams fall back to the drain journal's re-derivation).
        Exported streams are detached from this engine (slot and pages
        released, ``migrated_out`` counted) but their waiters are NOT
        resolved — the caller either adopts them on the peer
        (``migrate_import(payload, stream=s)``) or fails them and
        journals the recipe (:meth:`fail_stream` +
        :func:`migration_journal_entry`).  Non-exportable streams are
        left untouched for a subsequent :meth:`drain`."""
        import time as _time

        now = _time.monotonic()
        with self._lock:
            candidates = (
                list(streams) if streams is not None
                else [s for s in self._slots if s is not None]
            )
            exportable = [
                s for s in candidates
                if s.slot is not None
                and self._slots[s.slot] is s
                and not s.cancelled
                and not s.kv_export
                and s.kv_import is None
                and s.prefilled >= len(s.prompt)
                and self.speculative is None
            ]
        if not exportable:
            return []
        jnp = self._jnp
        # one bulk readback each for the tiny per-slot states; the page
        # gathers below are per-stream (each stream's table is its own)
        keys_np = np.asarray(self._keys)
        logits_np = np.asarray(self._logits)
        out: List[Tuple[Dict[str, Any], _Stream]] = []
        for s in exportable:
            slot = s.slot
            total = len(s.prompt) + len(s.tokens)
            if int(self._lengths[slot]) != total:
                # cursor/cache disagreement (should not happen outside a
                # mid-chunk call): refuse to snapshot inconsistent state
                logger.warning(
                    "migrate_export skipping req %d: cache length %d != "
                    "prompt+decoded %d", s.req_id,
                    int(self._lengths[slot]), total,
                )
                continue
            P = -(-total // self.page_size)
            idx = jnp.asarray(np.asarray(s.pages[:P], np.int32))
            payload = {
                "req_id": s.req_id,
                "prompt": np.asarray(s.prompt, np.int32),
                "tokens": np.asarray(s.tokens, np.int32),
                "k": np.asarray(self.pages_k[:, idx]),
                "v": np.asarray(self.pages_v[:, idx]),
                **(
                    {
                        "k_scales": np.asarray(self.scales_k[:, idx]),
                        "v_scales": np.asarray(self.scales_v[:, idx]),
                    }
                    if self._kv_int8 else {}
                ),
                "last_logits": logits_np[slot].astype(np.float32, copy=False),
                "key_data": keys_np[slot].copy(),
                "max_new_tokens": int(s.max_new),
                "temperature": float(s.temperature),
                "top_k": int(s.top_k),
                "eos_id": int(s.eos_id),
                "seed": int(s.seed),
                "priority": int(s.priority),
                "deadline_remaining_ms": (
                    max(0.0, (s.deadline - now) * 1000.0)
                    if s.deadline is not None else None
                ),
                "streamed": int(s.streamed),
                "stream_tokens": s.token_queue is not None,
                "adapter": s.adapter,
                "pending": s.pending,
                "page_size": self.page_size,
                "layout": "flat" if self._pool_flat else "split",
            }
            with self._lock:
                if self._slots[slot] is not s:
                    continue  # raced a concurrent retirement
                self._slots[slot] = None
                self._lengths[slot] = 0
                # close the LOCAL ledger: the work this engine spent on
                # the stream stays attributed here; the importing peer
                # opens a fresh ledger for its own share
                self._cost_close_locked(s)
                if s.pages:
                    self._free_locked(s.pages)
                    s.pages = []
                s.slot = None
                self._release_adapter_locked(s)
                self._counters["migrated_out"] += 1
            out.append((payload, s))
        self._flush_spans()
        return out

    def migrate_import(
        self,
        payload: Dict[str, Any],
        *,
        stream: Optional[_Stream] = None,
        stream_tokens: Optional[bool] = None,
    ) -> _Stream:
        """Admit a :meth:`migrate_export` payload: the prompt AND
        generated-token pages scatter in via the donated import path,
        the decode cursor/RNG/logits install exactly as the source held
        them, and decode resumes at the exact next token.

        ``stream`` (in-process evacuation) adopts the SOURCE engine's
        stream object — its waiter event and token queue keep working,
        so a streaming consumer sees an exact continuation across the
        migration with zero token loss.  Without it (the DCN form) a
        fresh stream is built from the payload's recipe;
        ``stream_tokens`` then forces/suppresses streaming (default:
        the payload's original mode)."""
        import time as _time

        prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
        tokens = np.asarray(payload.get("tokens", []), np.int32).reshape(-1)
        k = np.asarray(payload["k"])
        v = np.asarray(payload["v"])
        last = np.asarray(payload["last_logits"], np.float32).reshape(-1)
        ps = int(payload.get("page_size", self.page_size))
        if ps != self.page_size:
            raise MicroserviceError(
                f"migration payload page_size {ps} != engine page_size "
                f"{self.page_size}: source and target engines must share "
                "one pool configuration",
                status_code=400, reason="KV_LAYOUT_MISMATCH",
            )
        total = len(prompt) + len(tokens)
        P = -(-total // self.page_size)
        want = (self.module.num_layers, P) + tuple(self.pages_k.shape[2:])
        for name, arr in (("k", k), ("v", v)):
            if tuple(arr.shape) != want:
                raise MicroserviceError(
                    f"migration payload {name} shape {tuple(arr.shape)} "
                    f"does not fit this engine's pool geometry {want} "
                    "(layers, prompt+decoded pages, page tail)",
                    status_code=400, reason="KV_LAYOUT_MISMATCH",
                )
            if arr.dtype != np.dtype(self._pool_dtype):
                raise MicroserviceError(
                    f"migration payload {name} dtype {arr.dtype} != pool "
                    f"dtype {np.dtype(self._pool_dtype)}",
                    status_code=400, reason="KV_LAYOUT_MISMATCH",
                )
        if last.shape[0] != self.vocab_size:
            raise MicroserviceError(
                f"migration payload last_logits carries {last.shape[0]} "
                f"entries, engine vocab is {self.vocab_size}",
                status_code=400, reason="KV_LAYOUT_MISMATCH",
            )
        kv = {
            "k": k, "v": v, "last_logits": last, "tokens": tokens,
            "key_data": np.asarray(
                payload.get("key_data", []), np.uint32
            ).reshape(-1),
            "streamed": int(payload.get("streamed") or 0),
            "pending": payload.get("pending"),
            "migration": True,
        }
        if self._kv_int8:
            kv["k_scales"], kv["v_scales"] = self._validate_kv_scales(
                payload, P, "migration payload"
            )
        rem = payload.get("deadline_remaining_ms")
        deadline = (
            _time.monotonic() + max(0.0, float(rem)) / 1000.0
            if rem is not None else None
        )
        if stream is None:
            want_stream = (
                bool(payload.get("stream_tokens"))
                if stream_tokens is None else bool(stream_tokens)
            )
            return self.submit(
                prompt,
                max_new_tokens=int(payload.get("max_new_tokens", 32)),
                temperature=float(payload.get("temperature", 0.0)),
                top_k=int(payload.get("top_k", 0)),
                eos_id=int(payload.get("eos_id", -1)),
                seed=int(payload.get("seed", 0)),
                priority=int(payload.get("priority", 0)),
                deadline=deadline,
                stream_tokens=want_stream,
                adapter=payload.get("adapter") or None,
                kv_import=kv,
            )
        # ---- in-process adoption: the source's stream object joins
        # THIS engine's queue, waiter/event/token-queue intact ----------
        plen = len(prompt)
        max_new = int(stream.max_new)
        bucket = next((b for b in self.prompt_buckets if b >= plen), None)
        if bucket is None or plen + max_new > self.max_len:
            raise MicroserviceError(
                f"prompt {plen} + max_new {max_new} exceeds max_len "
                f"{self.max_len}",
                status_code=400, reason="SEQUENCE_TOO_LONG",
            )
        need = -(-(plen + max_new) // self.page_size)
        if need > self.num_pages - 1:
            raise MicroserviceError(
                f"request needs {need} pages but the pool holds "
                f"{self.num_pages - 1}",
                status_code=400, reason="SEQUENCE_TOO_LONG",
            )
        adapter = stream.adapter or None
        if adapter is not None:
            with self._lock:
                if self._closed:
                    raise MicroserviceError(
                        "engine closed", status_code=503,
                        reason="SHUTTING_DOWN",
                    )
                if self.max_queue and len(self._queue) >= self.max_queue:
                    self._shed_for_admission_locked(int(stream.priority))
        adapter_slot = (
            self._acquire_adapter_slot(adapter) if adapter is not None else 0
        )
        try:
            with self._lock:
                if self._closed:
                    raise MicroserviceError(
                        "engine closed", status_code=503,
                        reason="SHUTTING_DOWN",
                    )
                if self.max_queue and len(self._queue) >= self.max_queue:
                    self._shed_for_admission_locked(int(stream.priority))
                # the adopted object keeps its identity (event, token
                # queue, streamed cursor, trace linkage) and resets the
                # engine-local state the import wave will rebuild
                stream.slot = None
                stream.pages = []
                stream.cached_len = 0
                stream.prefilled = 0
                stream.tokens = []
                stream.kv_import = kv
                stream.kv_imported = False
                stream.kv_export = False
                stream.kv_payload = None
                stream.cancelled = False
                stream.preempted = False
                stream.error = None
                stream.result = None
                stream.deadline = deadline
                stream.adapter_slot = int(adapter_slot)
                if adapter_slot:
                    stream.adapter_pinned = True
                    self._drop_temp_pin_locked(adapter_slot)
                    self._adapter_requests[adapter] = (
                        self._adapter_requests.get(adapter, 0) + 1
                    )
                stream.queue_depth_at_submit = len(self._queue)
                self._queue.append(stream)
                self._queued.add(stream)
            return stream
        except BaseException:
            if adapter_slot:
                with self._lock:
                    self._drop_temp_pin_locked(adapter_slot)
                    self._unpin_adapter_slot_locked(adapter_slot)
            raise

    def fail_stream(self, stream: _Stream, exc: Exception) -> None:
        """Error-terminate one DETACHED stream (the migration fallback:
        an export whose peer import failed must resolve its waiter —
        with the journal recipe covering the re-derivation)."""
        with self._lock:
            if stream.result is not None or stream.error is not None:
                return
            self._fail_stream_locked(stream, exc)

    def predict_cost_s(
        self, prompt_len: int, max_new: int
    ) -> Optional[float]:
        """Predicted service seconds for one request from this engine's
        own measured rates (cumulative wall / cumulative tokens —
        stable after warmup, no tuning): the admission-pricing input
        disaggregated serving uses to fast-fail deadlines a request
        cannot meet BEFORE burning prefill on it.  ``None`` while the
        engine is cold (nothing measured yet — admit unpriced)."""
        with self._lock:
            ptok = self._counters["prefill_tokens"]
            pwall = self._counters["prefill_wall_s"]
            dtok = self._counters["tokens"]
            dwall = self._counters["chunk_wall_s"]
        if ptok <= 0 or pwall <= 0 or dtok <= 0 or dwall <= 0:
            return None
        return (
            float(prompt_len) * (pwall / ptok)
            + float(max_new) * (dwall / dtok)
        )

    def _ensure_pages_locked(self, stream: _Stream, per_chunk: Optional[int] = None) -> bool:
        """Grow the stream's block table to cover the next chunk."""
        slot = stream.slot
        if per_chunk is None:
            per_chunk = (
                self.draft_k + 1 if self.speculative is not None else self.steps_per_call
            )
        cap = len(stream.prompt) + stream.max_new
        if self.speculative is not None:
            cap += self.draft_k + 1  # the verify segment may scribble past
        horizon = min(
            int(self._lengths[slot]) + per_chunk,
            cap,
            self.max_len,
        )
        need = -(-horizon // self.page_size)
        if len(stream.pages) < need:
            self._cost_touch_locked(stream)
        while len(stream.pages) < need:
            got = self._alloc_locked(1)
            if got is None:
                return False
            self._block_tables[slot, len(stream.pages)] = got[0]
            stream.pages.extend(got)
        return True

    def _stream_push(self, stream: _Stream) -> None:
        """Push tokens the consumer has not seen yet (clamped to the
        stream's budget and cut at eos, matching _finish_locked's
        truncation so streamed == final result)."""
        q = stream.token_queue
        if q is None:
            return
        toks = stream.tokens[: stream.max_new]
        if stream.eos_id in toks:
            toks = toks[: toks.index(stream.eos_id) + 1]
        new = toks[stream.streamed :]
        if new:
            stream.streamed += len(new)
            q.put([int(t) for t in new])

    def _finish_locked(self, stream: _Stream) -> None:
        import time as _time

        slot = stream.slot
        stream.t_finish = _time.time()
        toks = stream.tokens[: stream.max_new]
        emitted_n = len(toks)
        eos = stream.eos_id
        if eos in toks:
            cut = toks.index(eos) + 1
            toks = toks[:cut] + [eos] * (stream.max_new - cut)
        toks = toks + [eos] * (stream.max_new - len(toks))
        stream.result = np.asarray(toks, np.int32)
        self._stream_push(stream)
        if stream.token_queue is not None:
            stream.token_queue.put(None)  # end-of-stream
        if stream.trace_id:
            import time as _time

            now = _time.time()
            if stream.t_decode_start:
                self._gen_span_deferred(
                    stream, "gen.decode", stream.t_decode_start,
                    max(0.0, now - stream.t_decode_start),
                    slot=slot, tokens=emitted_n,
                )
            finish_tags: Dict[str, Any] = dict(
                slot=slot, tokens=emitted_n,
                pages_held=len(stream.pages),
                cancelled=stream.cancelled,
            )
            if self._telemetry_enabled:
                # the cost ledger as span tags: the trace view of the
                # same numbers meta.tags.cost carries on the response
                self._cost_close_locked(stream)
                finish_tags["cost_page_s"] = round(stream.cost_page_s, 6)
                finish_tags["cost_prefill_tokens"] = stream.cost_prefill_tokens
                finish_tags["cost_decode_tokens"] = stream.cost_decode_tokens
                if stream.adapter:
                    finish_tags["cost_adapter"] = stream.adapter
            if self.speculative is not None:
                drafted = self._counters["spec_drafted"]
                finish_tags["spec_accept_rate"] = (
                    round(self._counters["spec_accepted"] / drafted, 3)
                    if drafted else 0.0
                )
            self._gen_span_deferred(stream, "gen.finish", now, 0.0, **finish_tags)
        self._cost_close_locked(stream)  # idempotent with the traced close
        self._tier_putback_locked(stream)
        self._slots[slot] = None
        self._free_locked(stream.pages)
        stream.pages = []
        self._lengths[slot] = 0
        self._release_adapter_locked(stream)
        self._counters["completed"] += 1
        stream.event.set()

    def _evict_locked(self, stream: _Stream) -> None:
        """Kick a stream out of its slot back to the queue head; it will
        re-prefill from scratch on re-admission."""
        import time as _time

        slot = stream.slot
        now = _time.time()
        if stream.trace_id:
            self._gen_span_deferred(
                stream, "gen.evict", now, 0.0,
                slot=slot, tokens_discarded=len(stream.tokens),
                pages_freed=len(stream.pages),
            )
        # restart the lifecycle clock (tracer or not — the bench reads
        # the raw stamps): the re-admitted run's gen.queued must measure
        # the RE-queue wait, not the first service attempt — otherwise
        # the decomposition blames served time on the queue-wait term
        # it exists to isolate
        stream.t_submit = now
        stream.t_prefill_start = 0.0
        stream.t_decode_start = 0.0
        # the re-derived run re-emits its first token: a stale stamp
        # would make TTFT (t_first_token - t_submit) go NEGATIVE after
        # the submit reset above
        stream.t_first_token = 0.0
        stream.queue_depth_at_submit = len(self._queue)
        # ledger: occupancy accrues up to the free, then pauses while
        # queued (cost_t = 0 marks "not holding pages"); tokens already
        # accrued stay — re-derivation after re-admission is MORE cost
        self._cost_touch_locked(stream)
        stream.cost_t = 0.0
        self._tier_putback_locked(stream)
        self._slots[slot] = None
        self._free_locked(stream.pages)
        stream.pages = []
        stream.tokens = []
        stream.slot = None
        stream.cached_len = 0  # re-admission re-matches the prefix index
        stream.prefilled = 0  # chunked prefill restarts (or re-imports)
        self._lengths[slot] = 0
        self._counters["evictions"] += 1
        self._queue.appendleft(stream)
        self._queued.add(stream)

    def cancel(self, stream: _Stream) -> None:
        """Abandon a stream (consumer disconnected): a queued stream is
        resolved immediately; an in-slot stream is flagged and the step
        loop retires it at its next bookkeeping point — never mid
        device-chunk, so slot/page state can't race the in-flight call.
        Its pages free and the slot re-admits the queue head."""
        with self._lock:
            if stream.result is not None or stream.error is not None:
                return
            if stream in self._queued:
                self._remove_queued_locked(stream)
                toks = stream.tokens[: stream.max_new]
                stream.result = np.asarray(
                    toks + [stream.eos_id] * (stream.max_new - len(toks)),
                    np.int32,
                )
                self._release_adapter_locked(stream)
                if stream.token_queue is not None:
                    stream.token_queue.put(None)
                stream.event.set()
                return
            stream.cancelled = True

    def _retire_cancelled_locked(self, active: List[_Stream]) -> List[_Stream]:
        """Finish flagged streams before the next chunk; returns the
        still-live subset.  Mid-decode deadline expiry retires here too
        — the same bookkeeping point the cancel path uses, so slot and
        page state can never race an in-flight device chunk."""
        import time as _time

        live = []
        now = None
        for stream in active:
            if stream.cancelled:
                self._finish_locked(stream)
                continue
            if stream.deadline is not None:
                now = _time.monotonic() if now is None else now
                if now >= stream.deadline:
                    self._counters["expired"] += 1
                    self._fail_stream_locked(
                        stream,
                        deadline_exceeded(
                            f"paged-engine decode (req {stream.req_id}, "
                            f"{len(stream.tokens)} tokens in)"
                        ),
                    )
                    continue
            live.append(stream)
        return live

    def _contain_chunk_fault(self, streams: List[_Stream], exc: Exception) -> bool:
        """Graceful degradation for an injected chunk failure: error out
        ONLY the streams that would have run this chunk (clean upstream
        503s), keep every other slot and the queue alive, and leave the
        allocator consistent — the chaos invariant is that ``fail_all``
        is never needed.  Returns step()'s has-more-work value."""
        err = MicroserviceError(
            f"decode chunk failed: {exc}",
            status_code=503, reason="ENGINE_CHUNK_FAULT",
        )
        with self._lock:
            self._counters["chunk_faults"] += 1
            for stream in streams:
                self._fail_stream_locked(stream, err)
            if self._debug_invariants:
                self._check_invariants_locked()
            more = bool(self._queue) or any(s is not None for s in self._slots)
        # the fault is a watchdog signal: a sustained fault rate drives
        # the engine health state machine toward degraded/evacuating
        self._feed_watchdog(0.0, fault=True)
        return more

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._queue) or any(s is not None for s in self._slots)

    def engine_stats(self, detail: bool = False) -> Dict[str, Any]:
        """Counters + live occupancy, the generation observability
        surface (jaxserver's batcher stats equivalent).

        The DEFAULT key set is under contract: every key is either
        mapped to a canonical Prometheus metric by
        ``GenerationPrometheusBridge`` or listed in its explicit
        exclusion set (tests/test_gen_observability.py), so a new
        counter cannot silently skip export.  ``detail=True`` adds the
        flight recorder's ring (per-chunk records) and its aggregates —
        the /debug/engine payload."""
        # device-health watchdog (r17): state string for the debug
        # surfaces, numeric code for the prometheus gauge (0 healthy /
        # 1 degraded / 2 evacuating), healthy->degraded trip count
        if self._watchdog is not None:
            from seldon_core_tpu.utils import watchdog as _wd

            health = self._watchdog.state
            health_code = _wd.STATE_CODES[health]
            watchdog_trips = self._watchdog.trips
        else:
            health, health_code, watchdog_trips = "healthy", 0, 0
        with self._lock:
            out = {
                **self._counters,
                "active_slots": sum(s is not None for s in self._slots),
                "queued_streams": len(self._queue),
                # mapped pages only: LRU-cached pages are reclaimable
                # capacity, reported under their own gauge below
                "pool_pages_used": (
                    self.num_pages - 1 - len(self._free_pages) - len(self._lru)
                ),
                "pool_pages_total": self.num_pages - 1,
                "prefix_pages_cached": len(self._lru),
                # tensor-parallel lane (r11): the degree this engine
                # runs at (1 = single-chip) and the PER-SHARD K+V pool
                # bytes one device actually holds — heads-sharded pools
                # shrink per-device residency by the degree, which is
                # what capacity planning prices (paged_hbm_accounting's
                # tp_degree term)
                "tp_degree": self.tp_degree,
                # serving-mesh data axis (r19): replica groups sharing
                # this engine's one weight residency; >1 also means the
                # pool's page dim is spread across the axis (unless
                # SELDON_TPU_SEQ_SHARD=0), which is what the
                # long-context capacity claim prices
                "dp_degree": self.dp_degree,
                "pool_shard_bytes": self._pool_shard_bytes,
                # chunked-prefill co-scheduling (r15): the wave token
                # budget this engine runs under (0 = monolithic prefill)
                "chunk_token_budget": self.chunk_token_budget,
                # multi-LoRA (r16): adapters resident in the factor
                # pool (pinned + LRU-cached) and the pool's slot count;
                # per-adapter request counts export with adapter labels
                # straight from the bridge (the flat mapping can't
                # carry labels — see ENGINE_STATS_EXCLUDED)
                "adapters_resident": len(self._adapter_table),
                "adapter_slots": self.max_adapters,
                "adapter_requests": dict(self._adapter_requests),
                # distinct compiled signatures seen by the jit sentinels
                # (prometheus gets the per-program split directly from
                # jitwatch — bridge-excluded to avoid double export)
                "jit_compiles": sum(s.compiles for s in self._sentinels.values()),
                "health": health,
                "health_state": health_code,
                "watchdog_trips": watchdog_trips,
                # fused paged-decode lane (r18): 1 when the per-step
                # attention runs the Pallas kernel, 0 on the XLA gather
                # fallback — dashboards must see which decode lane a
                # replica ACTUALLY runs (the TP/layout ineligibility
                # fallback used to degrade with only a one-shot WARN)
                "kernel_active": int(self._kernel_active),
                "kv_dtype_int8": int(self._kv_int8),
                # cost ledger (r20): per-adapter attribution split of
                # the cost_* counters above — labeled export from the
                # bridge, same shape as adapter_requests (excluded from
                # the flat mapping)
                "cost_by_adapter": {
                    k: dict(v) for k, v in self._cost_by_adapter.items()
                },
                # capture plane (r21): containers written and the
                # bounded store's on-disk footprint — popped below when
                # SELDON_TPU_CAPTURE=0 so the off lane sheds every new
                # stats key (same contract as the telemetry cost keys)
                "capture_store_bytes": 0,
                # hierarchical KV tier (r22): live bytes per level —
                # filled (with the 8 kv_tier_* counters kept) only when
                # SELDON_TPU_KV_OFFLOAD=1; the off lane pops all ten
                "kv_tier_host_bytes": 0,
                "kv_tier_disk_bytes": 0,
            }
        if self._capture_enabled:
            try:
                from seldon_core_tpu.utils import capture as _capture_mod

                out["capture_store_bytes"] = (
                    _capture_mod.default_store().total_bytes()
                )
            except Exception:  # noqa: BLE001 — stats must not break serving
                pass
        else:
            out.pop("captures", None)
            out.pop("capture_store_bytes", None)
        if self._kv_tier is not None:
            tier_stats = self._kv_tier.stats()
            out["kv_tier_host_bytes"] = tier_stats["host_bytes"]
            out["kv_tier_disk_bytes"] = tier_stats["disk_bytes"]
        else:
            for k in _TIER_COUNTER_KEYS + (
                "kv_tier_host_bytes", "kv_tier_disk_bytes",
            ):
                out.pop(k, None)
        if not self._telemetry_enabled:
            # SELDON_TPU_TELEMETRY=0 contract: no new metric series —
            # the bridge exports nothing it cannot see
            for k in (
                "cost_page_seconds",
                "cost_prefill_tokens",
                "cost_decode_tokens",
                "cost_by_adapter",
            ):
                out.pop(k, None)
        if detail:
            if self._watchdog is not None:
                out["watchdog"] = self._watchdog.stats()
            if self.recorder is not None:
                out["recorder"] = self.recorder.snapshot()
                out["recorder_stats"] = self.recorder.stats()
            else:
                out["recorder"] = []
                out["recorder_stats"] = {"records": 0, "seq": 0}
        return out

    @staticmethod
    def _journal_entry(s: _Stream, now: float) -> Dict[str, Any]:
        """One stream's re-derivation recipe as a drain-journal entry
        (the stream-object front of :func:`journal_entry` — the
        migration fallback builds the same schema from a payload via
        models/disagg.migration_journal_entry)."""
        return journal_entry(
            req_id=s.req_id,
            prompt=[int(t) for t in s.prompt],
            max_new_tokens=int(s.max_new),
            temperature=float(s.temperature),
            top_k=int(s.top_k),
            eos_id=int(s.eos_id),
            seed=int(s.seed),
            priority=int(s.priority),
            # absolute monotonic deadlines don't survive a
            # process: serialize the REMAINING budget and re-mint
            # on replay (wall time spent respawning decrements it
            # implicitly on neither side — acceptable: the
            # respawn window is the handoff's price)
            deadline_remaining_ms=(
                max(0.0, (s.deadline - now) * 1000.0)
                if s.deadline is not None else None
            ),
            # streaming resume: tokens the consumer already saw —
            # the replayed stream pushes only past this cursor,
            # so a reconnecting SSE consumer sees an exact
            # continuation, never a repeat
            streamed=int(s.streamed),
            stream_tokens=s.token_queue is not None,
            tokens_decoded=len(s.tokens),  # diagnostics only
            # the replayed stream must decode with the SAME
            # weight set; the respawned engine re-resolves the
            # name through its registry (cold-load on replay)
            adapter=s.adapter,
        )

    def drain(self) -> List[Dict[str, Any]]:
        """Drain for handoff (r12): stop admission, then serialize every
        live stream's RE-DERIVATION RECIPE — prompt, sampling knobs,
        seed, priority, remaining deadline, and the streaming cursor —
        to journal entries a respawned engine feeds to :meth:`replay`
        through the ordinary submit path.  Decoded tokens are NOT
        serialized: seeds are deterministic per stream, so the replay
        re-derives them bit-exactly (the same discipline the
        evict/restore path relies on), and the prompt pages usually come
        back for free through the prefix cache.

        Each journaled stream's local waiter is error-terminated with a
        503 ``DRAINING`` (the process is exiting; upstream callers retry
        through the normal transport path while the respawned engine
        re-derives proactively).  Call with the step loop quiesced — no
        chunk may be in flight (StreamingLM.drain joins the decode loop
        first; ``run()``-style callers are between steps by
        construction).  The engine is closed afterwards: admission
        never reopens on a drained engine."""
        import time as _time

        with self._lock:
            self._closed = True  # stops admission: submits now 503
            victims = [s for s in self._slots if s is not None] + list(self._queue)
            now = _time.monotonic()
            entries: List[Dict[str, Any]] = []
            for s in victims:
                if s.kv_export or s.kv_import is not None or s.kv_imported:
                    # disaggregated handoff streams are not journaled:
                    # the coordinating component retries the whole
                    # prefill-export / import round trip itself (a
                    # replayed import would need the payload persisted,
                    # and an export's waiter died with this process)
                    continue
                entries.append(self._journal_entry(s, now))
            self._queue.clear()
            self._queued.clear()
            err = MicroserviceError(
                "engine draining: stream journaled for handoff to the "
                "respawned engine",
                status_code=503, reason="DRAINING",
            )
            for s in victims:
                self._fail_stream_locked(s, err)
            self._counters["drained"] += len(victims)
        self._flush_spans()
        return entries

    def replay(
        self,
        entries: Sequence[Dict[str, Any]],
        stream_tokens: Optional[bool] = None,
    ) -> List[_Stream]:
        """Re-submit journaled streams (the restore half of
        drain/handoff).  ``stream_tokens=None`` honours each entry's
        original streaming mode and resumes its cursor; ``False`` forces
        unary replay (the respawn path uses this — the original
        consumers are gone, and an unread token queue would grow
        unbounded).  Entries whose remaining deadline is already spent
        are skipped (counted as ``expired``) — replaying them would burn
        the fresh engine's first admission wave on dead work.  Call
        before the step loop starts consuming (the streaming cursor must
        be in place before the first push)."""
        import time as _time

        out: List[_Stream] = []
        for e in entries:
            deadline = None
            rem = e.get("deadline_remaining_ms")
            if rem is not None:
                if float(rem) <= 0.0:
                    # the budget died BETWEEN journal write and replay
                    # (the respawn window ate it): skip with an expired
                    # count — submitting would only bounce off the
                    # fast-fail and mislabel the skip as a replay error
                    with self._lock:
                        self._counters["expired"] += 1
                    logger.warning(
                        "journal replay skipped req %s: deadline expired "
                        "between journal write and replay", e.get("req_id"),
                    )
                    continue
                deadline = _time.monotonic() + max(0.0, float(rem)) / 1000.0
            want_stream = (
                bool(e.get("stream_tokens"))
                if stream_tokens is None else bool(stream_tokens)
            )
            try:
                s = self.submit(
                    np.asarray(e["prompt"], np.int32),
                    max_new_tokens=int(e.get("max_new_tokens", 32)),
                    temperature=float(e.get("temperature", 0.0)),
                    top_k=int(e.get("top_k", 0)),
                    eos_id=int(e.get("eos_id", -1)),
                    seed=int(e.get("seed", 0)),
                    priority=int(e.get("priority", 0)),
                    deadline=deadline,
                    stream_tokens=want_stream,
                    adapter=e.get("adapter") or None,
                )
            except MicroserviceError as exc:
                logger.warning(
                    "journal replay skipped req %s: %s", e.get("req_id"), exc
                )
                continue
            if want_stream and e.get("streamed"):
                # resume exactly where the consumer left off: the
                # deterministic re-derivation regenerates the same
                # tokens, and the cursor suppresses the already-seen
                # prefix (no step loop has run yet — see docstring)
                s.streamed = int(e["streamed"])
            with self._lock:
                self._counters["replayed"] += 1
            out.append(s)
        return out

    def close(self, exc: Optional[Exception] = None) -> None:
        """Permanently shut the engine: future submits are rejected with
        503 and every pending stream is errored out (a submit that hangs
        because nothing will ever step it must fail instead)."""
        with self._lock:
            self._closed = True
        self.fail_all(
            exc or MicroserviceError(
                "engine closed", status_code=503, reason="SHUTTING_DOWN"
            )
        )
        # drop the engine-held registry pins: a closed engine's host
        # weight copies become reclaimable registry capacity
        if self._registry is not None:
            with self._adapter_io_lock:
                pinned, self._adapter_reg_pinned = (
                    self._adapter_reg_pinned, set()
                )
                for name in pinned:
                    self._registry.release(name)

    def fail_all(self, exc: Exception) -> None:
        """Error out every queued and in-flight stream, returning their
        pages to the pool — the engine stays usable afterwards."""
        with self._lock:
            victims = [s for s in self._slots if s is not None] + list(self._queue)
            self._queue.clear()
            self._queued.clear()
            for i in range(self.max_slots):
                self._slots[i] = None
            self._lengths[:] = 0
            for stream in victims:
                self._cost_close_locked(stream)
                self._tier_putback_locked(stream)
                if stream.pages:
                    self._free_locked(stream.pages)
                    stream.pages = []
                stream.error = exc
                self._release_adapter_locked(stream)
                if stream.token_queue is not None:
                    stream.token_queue.put(None)  # unblock the consumer
                stream.event.set()

    def _record_prefill_wave(
        self, *, wall_s: float, tokens: int, occupancy: int,
        admissions: int, stalls: int, pre_hits: int, pre_saved: int,
        pre_slo: Dict[str, int], puids=(), pre_tier=None,
    ) -> bool:
        """Record a wave that carried ONLY prefill work — budgeted
        prefill-only waves AND waves whose streams all finished at
        prefill (kv_export workers, spec max_new=1).  Without this the
        recorder's window mix undercounts against the prefill_tokens
        counter exactly on pure prefill workers.  Returns step()'s
        has-more-work value."""
        with self._lock:
            if self._debug_invariants:
                self._check_invariants_locked()
            more = bool(self._queue) or any(
                s is not None for s in self._slots
            )
            queue_depth = len(self._queue)
            prefix_hits_d = self._counters["prefix_hits"] - pre_hits
            prefix_saved_d = (
                self._counters["prefix_tokens_saved"] - pre_saved
            )
            slo_d = {
                k: self._counters[k] - pre_slo[k]
                for k in _SLO_COUNTER_KEYS
            }
            # KV tier deltas ride the record only when the tier is on:
            # the off lane's chunk records stay byte-identical
            tier_d = (
                {k: self._counters[k] - pre_tier[k] for k in _TIER_DELTA_KEYS}
                if pre_tier is not None else {}
            )
            pages_cached = len(self._lru)
        self._record_chunk({
            "phase": "prefill",
            # puid linkage (r21): breach dumps index the requests the
            # wave actually carried, not just an anonymous ring slice
            "puids": list(puids),
            "wall_ms": round(wall_s * 1000.0, 3),
            "prefill_wall_ms": round(wall_s * 1000.0, 3),
            "tp_degree": self.tp_degree,
            "dp_degree": self.dp_degree,
            "steps": 0,
            "buckets": [],
            "occupancy": occupancy,
            "admissions": admissions,
            "stalls": stalls,
            "queue_depth": queue_depth,
            "tokens": tokens,
            "prefill_tokens": tokens,
            "decode_tokens": 0,
            "prefix_hits": prefix_hits_d,
            "prefix_tokens_saved": prefix_saved_d,
            "prefix_pages_cached": pages_cached,
            **slo_d,
            **tier_d,
        })
        return more

    def step(self) -> bool:
        """Admit + prefill joiners, run one decode chunk, retire finished.

        Returns True while there is (or may be) more work.
        """
        try:
            if self.speculative is not None:
                return self._step_speculative()
            return self._step_decode()
        finally:
            # spans queued inside _lock-held retire/evict code emit here,
            # after every lock has dropped (a JSONL-exporting tracer does
            # disk I/O) — including on the early-return paths
            self._flush_spans()

    def _step_decode(self) -> bool:
        jnp = self._jnp
        with self._lock:
            # pre-admission prefix + SLO counters: the chunk record
            # carries this wave's deltas (flight-recorder contract)
            pre_hits = self._counters["prefix_hits"]
            pre_saved = self._counters["prefix_tokens_saved"]
            pre_slo = {k: self._counters[k] for k in _SLO_COUNTER_KEYS}
            pre_tier = (
                {k: self._counters[k] for k in _TIER_DELTA_KEYS}
                if self._kv_tier is not None else None
            )
            admitted = self._admit_locked()
        # KV tier (r22): admissions' promoted chains scatter before any
        # prefill or decode work touches the wave (no-op when off)
        self._tier_promote_ready()
        budget = self.chunk_token_budget
        wave_prefill_tokens = 0
        wave_prefill_wall = 0.0
        if not budget:
            # monolithic prefill (the historical wave shape): admitted
            # prompts prefill whole, then decode in this same wave
            _done, wave_prefill_tokens, wave_prefill_wall = (
                self._prefill_streams([s for s, _ in admitted])
            )

        with self._lock:
            self._counters["prefills"] += len(admitted)
            active = self._retire_cancelled_locked(
                [s for s in self._slots if s is not None]
            )
        if not active:
            # every admitted stream finished AT prefill (kv_export
            # workers, cancellations): the wave still carried prefill
            # work and must be recorded, or a pure prefill worker's
            # window mix reads zero
            if wave_prefill_tokens:
                return self._record_prefill_wave(
                    wall_s=wave_prefill_wall, tokens=wave_prefill_tokens,
                    occupancy=0, admissions=len(admitted), stalls=0,
                    pre_hits=pre_hits, pre_saved=pre_saved,
                    pre_slo=pre_slo, pre_tier=pre_tier,
                    puids=[s.puid for s, _ in admitted if s.puid],
                )
            with self._lock:
                return bool(self._queue)
        with self._lock:
            if budget:
                # chunked co-scheduling (r15): only fully-prefilled
                # streams decode THIS wave — a stream whose final slice
                # runs below starts decoding next wave, which is what
                # bounds the wave at the token budget (its lane stays
                # masked in done_in)
                decoding = [
                    s for s in active if s.prefilled >= len(s.prompt)
                ]
                prefilling = [
                    s for s in active if s.prefilled < len(s.prompt)
                ]
            else:
                decoding, prefilling = list(active), []
            # saturated-decode ladder: with nothing waiting for a slot,
            # bigger chunks amortise the per-call round-trip; a waiting
            # queue (or a chunked-prefill backlog, which needs wave
            # cadence for its slices) pins the short chunk so admission
            # latency stays bounded by the chunk length.  Each doubling
            # is taken only if the POOL can back it for every decoding
            # stream — otherwise a shrunk pool would mass-stall and the
            # evict/re-admit cycle would discard decoded progress that
            # base-size chunks were making steadily.
            steps = self.steps_per_call
            if decoding and not self._queue and not prefilling:
                most = max(s.max_new - len(s.tokens) for s in decoding)
                free = self._allocatable_locked()  # LRU-cached pages reclaim on demand
                while steps * 2 <= self.max_steps and steps < most:
                    nxt = steps * 2
                    need = 0
                    for s in decoding:
                        horizon = min(
                            int(self._lengths[s.slot]) + nxt,
                            len(s.prompt) + s.max_new,
                            self.max_len,
                        )
                        need += max(
                            0, -(-horizon // self.page_size) - len(s.pages)
                        )
                    if need > free:
                        break
                    steps = nxt
            stalled = np.zeros((self.max_slots,), bool)
            for stream in decoding:
                if not self._ensure_pages_locked(stream, per_chunk=steps):
                    stalled[stream.slot] = True
            self._counters["stalls"] += int(stalled.sum())
            # every decoding stream stalled on pool pressure: evict
            # victims (least progress lost, ties to the youngest) back to
            # the head of the queue until someone can run.  Seeds are
            # deterministic per stream, so a re-run reproduces the same
            # tokens — callers see latency, never corruption.  Terminates
            # because a lone stream always fits (submit() rejects need >
            # num_pages-1).  With a chunked-prefill backlog the eviction
            # loop stands down: prefill slices ARE progress this wave,
            # and their completions turn into decoders next wave.
            while (
                decoding and not prefilling
                and all(stalled[s.slot] for s in decoding)
            ):
                victim = min(decoding, key=lambda s: (len(s.tokens), -s.req_id))
                decoding.remove(victim)
                self._evict_locked(victim)
                for stream in decoding:
                    if stalled[stream.slot] and self._ensure_pages_locked(
                        stream, per_chunk=steps
                    ):
                        stalled[stream.slot] = False
            if not decoding and not prefilling:
                return bool(self._queue)
            runnable_now = [s for s in decoding if not stalled[s.slot]]
            if budget and runnable_now:
                # decode admitted FIRST: never squeezed below one step,
                # but capped so decode + prefill stay inside the budget
                steps = min(steps, max(1, budget // len(runnable_now)))
            slices = (
                self._plan_prefill_slices_locked(
                    prefilling, budget - steps * len(runnable_now)
                )
                if budget else []
            )
            done_in = np.ones((self.max_slots,), bool)
            max_new = np.zeros((self.max_slots,), np.int32)
            temps = np.zeros((self.max_slots,), np.float32)
            top_ks = np.zeros((self.max_slots,), np.int32)
            eos_ids = np.full((self.max_slots,), -1, np.int32)
            for stream in decoding:
                s = stream.slot
                done_in[s] = stalled[s]
                max_new[s] = stream.max_new - len(stream.tokens)
                temps[s] = stream.temperature
                top_ks[s] = stream.top_k
                eos_ids[s] = stream.eos_id
            pages_h = self._pages_horizon(runnable_now, steps)
            # ctx horizons for the chunk: per length bucket (the ring
            # impl gathers only pages holding tokens that EXIST at
            # chunk start — in-chunk tokens live in the ring; the pool
            # impl's per-step tables add this chunk's growth)
            buckets, perm = self._plan_buckets(runnable_now, steps, pages_h)
            tables = jnp.asarray(self._block_tables[:, :pages_h])
            lengths = jnp.asarray(self._lengths)
            emitted0 = jnp.zeros((self.max_slots,), jnp.int32)
            # multi-LoRA (r16): the wave's per-lane adapter slot ids —
            # a TRACED argument, so any mix of adapters runs this same
            # compiled program (idle lanes gather harmlessly)
            adapter_wave = (
                self._adapter_slots.copy() if self._lora is not None else None
            )
            if self._lora is not None:
                live_slots = {
                    int(adapter_wave[s.slot]) for s in runnable_now
                }
                if len(live_slots) > 1 and any(live_slots):
                    self._counters["multi_adapter_chunks"] += 1

        import time as _time

        # chunked-prefill slices run BEFORE the decode chunk: the wave's
        # budget covers both, and streams completing here decode next
        # wave (their lanes stay masked in this chunk's done_in)
        if slices:
            _done, ptok, pwall = self._run_prefill_slices(slices)
            wave_prefill_tokens += ptok
            wave_prefill_wall += pwall
        if not runnable_now:
            # prefill-only wave: no decode lane could run, but slices
            # made progress (or every decoder awaits pages a chunking
            # prompt still holds) — record the wave so the scheduler's
            # chunk mix stays observable
            if wave_prefill_tokens:
                return self._record_prefill_wave(
                    wall_s=wave_prefill_wall, tokens=wave_prefill_tokens,
                    occupancy=len(active), admissions=len(admitted),
                    stalls=int(stalled.sum()), pre_hits=pre_hits,
                    pre_saved=pre_saved, pre_slo=pre_slo,
                    pre_tier=pre_tier,
                    puids=[s.puid for s in active if s.puid],
                )
            with self._lock:
                if self._debug_invariants:
                    self._check_invariants_locked()
                return bool(self._queue) or any(
                    s is not None for s in self._slots
                )

        try:
            # fault point paged.chunk fires BEFORE the device call is
            # issued, so pool buffers stay valid and only this chunk's
            # runnable streams fail — graceful containment, never
            # fail_all (a REAL device error later in this function
            # still escalates through the loop's fail_all path, since
            # donated buffers may be gone by then)
            _faults.raise_if("paged.chunk")
        except _faults.InjectedFault as exc:
            return self._contain_chunk_fault(runnable_now, exc)
        # KV tier (r22): decode-growth allocations above may have
        # staged demotions — gather them before the chunk writes the
        # pool (no-op when off)
        self._tier_flush()
        self._profile_before_chunk()
        t_chunk = _time.perf_counter()
        chunk_args = (
            self.params, *self._kv_args(), self._lane_put(self._logits),
            lengths, tables, self._lane_put(self._keys),
            jnp.asarray(done_in),
            emitted0, jnp.asarray(max_new), jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(eos_ids), jnp.asarray(perm),
        )
        if self._lora is not None:
            chunk_args = chunk_args + (
                self._lora.device_args(), jnp.asarray(adapter_wave),
            )
        toks, pk_out, pv_out, self._logits, lengths_out, self._keys, _, emitted = (
            self._get_chunk(steps, buckets)(*chunk_args)
        )
        self._store_kv(pk_out, pv_out)
        toks_np = np.asarray(toks)
        emitted_np = np.asarray(emitted)
        # single-writer window: the chunk runs with its streams pinned
        # and admission only mutates lengths between chunks under the lock
        # graftlint: allow[lock-discipline] — single-writer chunk window
        self._lengths = np.array(lengths_out)  # copy: jax views are read-only
        chunk_wall = _time.perf_counter() - t_chunk
        self._profile_after_chunk()
        # poison-stream quarantine BEFORE harvest: a lane whose served
        # logits went non-finite must not deliver this chunk's tokens
        # (they were computed alongside the poison) — it retires with
        # 500 NUMERIC_POISON while its wave-mates harvest normally
        self._quarantine_poisoned(runnable_now)

        with self._lock:
            self._counters["chunks"] += 1
            self._counters["bucketed_chunks"] += int(len(buckets) > 1)
            self._counters["chunk_wall_s"] += chunk_wall
            chunk_tokens = 0
            t_now = _time.time()
            for stream in decoding:
                if stream.error is not None:
                    continue  # quarantined by the NaN screen pre-harvest
                s = stream.slot
                if stalled[s]:
                    continue
                n = int(emitted_np[s])
                self._counters["tokens"] += n
                chunk_tokens += n
                stream.cost_decode_tokens += n
                got = toks_np[s, :n].tolist()
                if got and not stream.tokens and not stream.t_first_token:
                    # TTFT numerator: the stream's first decode token
                    # landed in this chunk (chunk-boundary resolution —
                    # the finest the host observes)
                    stream.t_first_token = t_now
                stream.tokens.extend(got)
                hit_eos = stream.eos_id in got
                if hit_eos or len(stream.tokens) >= stream.max_new:
                    self._finish_locked(stream)
                else:
                    self._stream_push(stream)
            if self._debug_invariants:  # chunk-boundary allocator audit
                self._check_invariants_locked()
            more = bool(self._queue) or any(s is not None for s in self._slots)
            queue_depth = len(self._queue)
            prefix_hits_d = self._counters["prefix_hits"] - pre_hits
            prefix_saved_d = self._counters["prefix_tokens_saved"] - pre_saved
            slo_d = {k: self._counters[k] - pre_slo[k] for k in _SLO_COUNTER_KEYS}
            tier_d = (
                {k: self._counters[k] - pre_tier[k] for k in _TIER_DELTA_KEYS}
                if pre_tier is not None else {}
            )
            pages_cached = len(self._lru)
            # exemplar seed: any traced stream in the wave links this
            # chunk's duration observation back to one real trace
            chunk_trace = ""
            if self._telemetry_enabled:
                chunk_trace = next(
                    (s.trace_id for s in decoding if s.trace_id), ""
                )
            # puid linkage (r21): breach dumps index the requests
            # active in the wave instead of staying an anonymous ring
            wave_puids = sorted(
                {s.puid for s in active if s.puid}
            )
        self._record_chunk({
            "phase": "decode",
            "puids": wave_puids,
            "trace_id": chunk_trace,
            "wall_ms": round(chunk_wall * 1000.0, 3),
            "prefill_wall_ms": round(wave_prefill_wall * 1000.0, 3),
            "tp_degree": self.tp_degree,
            "dp_degree": self.dp_degree,
            "steps": steps,
            "buckets": [list(b) for b in buckets],
            "occupancy": len(active),
            "admissions": len(admitted),
            "stalls": int(stalled.sum()),
            "queue_depth": queue_depth,
            # the wave's token mix: "tokens" is the TOTAL work the wave
            # carried (the budgeted quantity); the split is what the
            # chunk-mix observability reads (r15 — "tokens" used to
            # conflate the two on admission waves)
            "tokens": chunk_tokens + wave_prefill_tokens,
            "prefill_tokens": wave_prefill_tokens,
            "decode_tokens": chunk_tokens,
            "prefix_hits": prefix_hits_d,
            "prefix_tokens_saved": prefix_saved_d,
            "prefix_pages_cached": pages_cached,
            **slo_d,
            **tier_d,
        })
        return more

    def _step_speculative(self) -> bool:
        """One draft/verify round for every active slot.

        Drafting is host-side ngram lookup on each stream's own context
        (per-slot: streams draft independently), verification is one
        batched forward — speculative decode and continuous batching
        compose instead of being separate lanes.
        """
        import time as _time

        from seldon_core_tpu.models.speculative import ngram_draft

        jnp = self._jnp
        with self._lock:
            pre_hits = self._counters["prefix_hits"]
            pre_saved = self._counters["prefix_tokens_saved"]
            pre_slo = {k: self._counters[k] for k in _SLO_COUNTER_KEYS}
            pre_tier = (
                {k: self._counters[k] for k in _TIER_DELTA_KEYS}
                if self._kv_tier is not None else None
            )
            admitted = self._admit_locked()
        # KV tier (r22): promoted chains scatter before the wave's
        # prefill/verify work (no-op when off)
        self._tier_promote_ready()
        budget = self.chunk_token_budget
        wave_prefill_tokens = 0
        wave_prefill_wall = 0.0
        fresh: List[_Stream] = []
        slices: List[Tuple[_Stream, int, int]] = []
        if not budget:
            fresh, wave_prefill_tokens, wave_prefill_wall = (
                self._prefill_streams([s for s, _ in admitted])
            )
        else:
            # chunked co-scheduling, verify-first: every fully-prefilled
            # stream's verify forward is priced at its fixed width
            # (draft_k+1 — verification cannot shrink), the rest of the
            # budget goes to prompt slices
            with self._lock:
                live = [s for s in self._slots if s is not None]
                verify_lanes = sum(
                    1 for s in live if s.prefilled >= len(s.prompt)
                )
                slices = self._plan_prefill_slices_locked(
                    [s for s in live if s.prefilled < len(s.prompt)],
                    budget - verify_lanes * (self.draft_k + 1),
                )
            if slices:
                fresh, wave_prefill_tokens, wave_prefill_wall = (
                    self._run_prefill_slices(slices)
                )

        with self._lock:
            self._counters["prefills"] += len(admitted)
            t_now = _time.time()
            for stream in fresh:
                # the prefill's argmax IS the first generated token:
                # emit it now so round 1 verifies continuations of it
                # (pending == tokens[-1] is the loop invariant)
                if stream.result is not None or stream.error is not None:
                    continue
                if not stream.t_first_token:
                    stream.t_first_token = t_now
                stream.tokens.append(int(stream.pending))
                self._counters["tokens"] += 1
                if stream.pending == stream.eos_id or len(stream.tokens) >= stream.max_new:
                    self._finish_locked(stream)
                else:
                    self._stream_push(stream)
            active = self._retire_cancelled_locked(
                [s for s in self._slots if s is not None]
            )
            if not active:
                wave_done_early = True
            else:
                wave_done_early = False
        if wave_done_early:
            # every stream finished at/with prefill (kv_export, or the
            # pending-append completed max_new==1 streams): still a
            # prefill wave the recorder must see
            if wave_prefill_tokens:
                return self._record_prefill_wave(
                    wall_s=wave_prefill_wall, tokens=wave_prefill_tokens,
                    occupancy=0, admissions=len(admitted), stalls=0,
                    pre_hits=pre_hits, pre_saved=pre_saved,
                    pre_slo=pre_slo, pre_tier=pre_tier,
                    puids=[s.puid for s, _ in admitted if s.puid],
                )
            with self._lock:
                return bool(self._queue)
        with self._lock:
            # chunked: streams mid-prefill never verify, and streams
            # whose final slice ran THIS wave verify next wave (that is
            # what keeps the wave inside its planned token count)
            fresh_ids = {id(s) for s in fresh} if budget else set()
            verify_set = [
                s for s in active
                if s.prefilled >= len(s.prompt) and id(s) not in fresh_ids
            ]
            stalled = np.zeros((self.max_slots,), bool)
            for stream in verify_set:
                if not self._ensure_pages_locked(stream):
                    stalled[stream.slot] = True
            self._counters["stalls"] += int(stalled.sum())
            # eviction stands down ONLY when this wave's prefill slices
            # actually progressed — gating on a mere backlog would
            # livelock when every verify lane is page-starved AND the
            # verify-first pricing left the planner under one page
            # (stalled lanes were priced in): no slice, no verify, and
            # no eviction would ever run
            while (
                verify_set and not slices
                and all(stalled[s.slot] for s in verify_set)
            ):
                victim = min(
                    verify_set, key=lambda s: (len(s.tokens), -s.req_id)
                )
                verify_set.remove(victim)
                active.remove(victim)
                self._evict_locked(victim)
                for stream in verify_set:
                    if stalled[stream.slot] and self._ensure_pages_locked(stream):
                        stalled[stream.slot] = False
            if not active:
                return bool(self._queue)
            L = self.draft_k + 1
            segs = np.zeros((self.max_slots, L), np.int32)
            n_drafts = np.zeros((self.max_slots,), np.int32)
            active_mask = np.zeros((self.max_slots,), bool)
            runnable = [s for s in verify_set if not stalled[s.slot]]
            mode = self.speculative["draft"]
            model_drafts = None
            if mode == "model" and runnable:
                # one batched rollout call for every runnable slot (the
                # draft is small; through a relayed host this adds one
                # round-trip per round — on attached hardware it is
                # microseconds).  Windows end at each stream's pending
                # token (tokens[-1] — the loop invariant), so drafts
                # continue exactly the sequence the verify checks.
                W = self.draft_window
                windows = np.zeros((self.max_slots, W), np.int32)
                lens = np.zeros((self.max_slots,), np.int32)
                for stream in runnable:
                    ctx = np.concatenate(
                        [stream.prompt, np.asarray(stream.tokens, np.int32)]
                    )
                    tail = ctx[-W:]
                    windows[stream.slot, : len(tail)] = tail
                    lens[stream.slot] = len(tail)
                model_drafts = np.asarray(
                    self._draft_rollout(
                        self._draft_params, jnp.asarray(windows), jnp.asarray(lens)
                    )
                )
            for stream in runnable:
                slot = stream.slot
                # never draft past the stream's budget: each accepted
                # draft + the bonus token advance the stream, so only
                # remaining-1 drafts can ever be emitted — extra drafts
                # would burn verify width and inflate acceptance stats
                # with tokens _finish_locked discards
                remaining = stream.max_new - len(stream.tokens)
                k_eff = max(0, min(self.draft_k, remaining - 1))
                if k_eff == 0:
                    drafted = np.zeros((0,), np.int32)
                elif mode == "oracle" and stream.draft_hint is not None:
                    done = len(stream.tokens)
                    drafted = stream.draft_hint[done : done + k_eff]
                elif mode == "model":
                    drafted = model_drafts[slot, :k_eff]
                else:
                    context = np.concatenate(
                        [stream.prompt, np.asarray(stream.tokens, np.int32)]
                    )
                    drafted = ngram_draft(
                        context, k_eff, ngram=int(self.speculative["ngram"])
                    )[:k_eff]
                segs[slot, 0] = stream.pending
                segs[slot, 1 : 1 + len(drafted)] = drafted
                n_drafts[slot] = len(drafted)
                active_mask[slot] = True
                self._counters["spec_drafted"] += len(drafted)
            pages_h = self._pages_horizon(runnable, self.draft_k + 1)
            tables = jnp.asarray(self._block_tables[:, :pages_h])
            lengths = jnp.asarray(self._lengths)
            adapter_wave = (
                self._adapter_slots.copy() if self._lora is not None else None
            )
            if self._lora is not None:
                live_slots = {int(adapter_wave[s.slot]) for s in runnable}
                if len(live_slots) > 1 and any(live_slots):
                    self._counters["multi_adapter_chunks"] += 1

        if not runnable:
            # nothing to verify this wave; prefill slices (or the
            # freshly-completed streams now waiting a wave) are the
            # progress — there is more work by construction
            return True

        try:  # same pre-device-call containment as the decode path
            _faults.raise_if("paged.chunk")
        except _faults.InjectedFault as exc:
            return self._contain_chunk_fault(runnable, exc)
        # KV tier (r22): verify-lane page growth may have staged
        # demotions — gather before the chunk writes the pool
        self._tier_flush()
        self._profile_before_chunk()
        t_chunk = _time.perf_counter()
        spec_args = (
            self.params, *self._kv_args(), jnp.asarray(segs),
            jnp.asarray(n_drafts), jnp.asarray(active_mask), tables, lengths,
        )
        if self._lora is not None:
            spec_args = spec_args + (
                self._lora.device_args(), jnp.asarray(adapter_wave),
            )
        out, counts, pk_out, pv_out, lengths_out = self._spec_chunk(
            *spec_args
        )
        self._store_kv(pk_out, pv_out)
        out_np = np.asarray(out)
        counts_np = np.asarray(counts)
        # same single-writer window as the decode chunk: streams
        # pinned, admission between chunks
        # graftlint: allow[lock-discipline] — single-writer chunk window
        self._lengths = np.array(lengths_out)
        chunk_wall = _time.perf_counter() - t_chunk
        self._profile_after_chunk()

        with self._lock:
            self._counters["chunks"] += 1
            self._counters["chunk_wall_s"] += chunk_wall
            chunk_tokens = 0
            for stream in runnable:
                s = stream.slot
                n = int(counts_np[s])
                got = out_np[s, :n].tolist()
                self._counters["tokens"] += n
                chunk_tokens += n
                stream.cost_decode_tokens += n
                self._counters["spec_accepted"] += max(0, n - 1)
                stream.tokens.extend(got)
                stream.pending = int(got[-1]) if got else stream.pending
                hit_eos = stream.eos_id in got
                if hit_eos or len(stream.tokens) >= stream.max_new:
                    self._finish_locked(stream)
                else:
                    self._stream_push(stream)
            if self._debug_invariants:  # chunk-boundary allocator audit
                self._check_invariants_locked()
            more = bool(self._queue) or any(s is not None for s in self._slots)
            queue_depth = len(self._queue)
            prefix_hits_d = self._counters["prefix_hits"] - pre_hits
            prefix_saved_d = self._counters["prefix_tokens_saved"] - pre_saved
            slo_d = {k: self._counters[k] - pre_slo[k] for k in _SLO_COUNTER_KEYS}
            tier_d = (
                {k: self._counters[k] - pre_tier[k] for k in _TIER_DELTA_KEYS}
                if pre_tier is not None else {}
            )
            pages_cached = len(self._lru)
            chunk_trace = ""
            if self._telemetry_enabled:
                chunk_trace = next(
                    (s.trace_id for s in runnable if s.trace_id), ""
                )
            wave_puids = sorted(
                {s.puid for s in active if s.puid}
            )
        self._record_chunk({
            "phase": "spec_verify",
            "puids": wave_puids,
            "trace_id": chunk_trace,
            "wall_ms": round(chunk_wall * 1000.0, 3),
            "prefill_wall_ms": round(wave_prefill_wall * 1000.0, 3),
            "tp_degree": self.tp_degree,
            "dp_degree": self.dp_degree,
            "steps": self.draft_k + 1,
            "buckets": [],
            "occupancy": len(active),
            "admissions": len(admitted),
            "stalls": int(stalled.sum()),
            "queue_depth": queue_depth,
            "tokens": chunk_tokens + wave_prefill_tokens,
            "prefill_tokens": wave_prefill_tokens,
            "decode_tokens": chunk_tokens,
            "prefix_hits": prefix_hits_d,
            "prefix_tokens_saved": prefix_saved_d,
            "prefix_pages_cached": pages_cached,
            **slo_d,
            **tier_d,
        })
        return more

    def run(self) -> None:
        """Drain everything synchronously (test / batch-job entrypoint)."""
        while self.has_work():
            self.step()

    def generate(self, prompt, **kw) -> np.ndarray:
        """Synchronous one-shot convenience around submit + run."""
        stream = self.submit(np.asarray(prompt), **kw)
        self.run()
        if stream.error:
            raise stream.error
        return stream.result


# process-wide id source for bridge labels: each engine gets a distinct
# model_name so shared-registry timeseries never merge across engines
_BRIDGE_SEQ = 0
_BRIDGE_SEQ_LOCK = threading.Lock()


class StreamingLM(TPUComponent):
    """Deployable continuous-batching generation component.

    Concurrent ``predict`` calls share one :class:`PagedEngine`: each
    request's rows become streams, a background loop steps the engine,
    and every caller blocks only until *its* streams finish — short
    generations return while long ones keep decoding (contrast
    :class:`GenerativeLM`, which batches rectangularly per request).

    Per-request overrides via ``meta.tags``: ``max_new_tokens``,
    ``temperature``, ``top_k``, ``seed``.
    """

    device_exclusive = True  # TPU-resident weights/KV: one process per chip

    def __init__(
        self,
        vocab_size: int = 32000,
        d_model: int = 256,
        num_layers: int = 4,
        num_heads: int = 8,
        max_len: int = 2048,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        eos_id: int = -1,
        model_uri: str = "",
        seed: int = 0,
        page_size: int = 64,
        num_pages: int = 0,
        max_slots: int = 8,
        steps_per_call: int = 8,
        max_steps_per_call: int = 0,
        mesh_axes: Optional[Dict[str, int]] = None,
        tp: int = 0,
        dp: int = 0,
        quantize: str = "",
        precision: str = "",
        speculative: Optional[Dict[str, Any]] = None,
        prefix_cache: Optional[bool] = None,
        max_queue: int = 0,
        chunk_token_budget: int = 0,
        max_adapters: int = 0,
        lora_rank: int = 8,
        adapters: Any = None,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.config = dict(
            vocab_size=int(vocab_size), d_model=int(d_model),
            num_layers=int(num_layers), num_heads=int(num_heads),
            max_len=int(max_len),
        )
        from seldon_core_tpu.ops.surgery import (
            validate_precision,
            validate_quantize_mode,
        )

        self.engine_config = dict(
            page_size=int(page_size), num_pages=int(num_pages) or None,
            max_slots=int(max_slots), steps_per_call=int(steps_per_call),
            max_steps_per_call=int(max_steps_per_call),
            quantize=validate_quantize_mode(quantize),  # fail at construction
            precision=validate_precision(precision),
            # speculative={"draft": "ngram", "draft_k": k, "ngram": n}:
            # per-slot draft/verify INSIDE the continuous-batching
            # engine — greedy-exact, one verify forward per chunk
            speculative=dict(speculative) if speculative else None,
            # page-granular automatic prefix caching: None defers to
            # SELDON_TPU_PREFIX_CACHE (default on; "0" disables)
            prefix_cache=prefix_cache,
            # bounded run queue with priority shedding (0 defers to
            # SELDON_TPU_MAX_QUEUE; 0 = unbounded)
            max_queue=int(max_queue),
            # chunked-prefill co-scheduling (0 defers to
            # SELDON_TPU_CHUNK_TOKEN_BUDGET; 0 = monolithic prefill)
            chunk_token_budget=int(chunk_token_budget),
        )
        # multi-LoRA (r16): adapter pool slots (0 defers to
        # SELDON_TPU_MAX_ADAPTERS; 0 = adapters off) + the factor rank
        # every registered adapter must share (one pool shape), and the
        # deployment's named adapter catalogue — dict name -> spec
        # ({"seed": n} deterministic synthetic factors, {"uri": ...} a
        # msgpack checkpoint) registered into the process weight
        # registry at load (loaders: nothing materialises until a
        # request selects it).  Deployment parameters arrive as JSON.
        self.max_adapters = int(max_adapters)
        self.lora_rank = int(lora_rank)
        if isinstance(adapters, str):
            import json as _json

            adapters = _json.loads(adapters) if adapters else None
        self.adapters = dict(adapters) if adapters else {}
        self.mesh_axes = dict(mesh_axes) if mesh_axes else None
        # serving-mesh degrees (r11 tp, r19 dp): `tp=N` / `dp=D` (or
        # SELDON_TPU_TP / SELDON_TPU_DP when 0) are the deployment-
        # facing spelling of mesh_axes={"data": D, "model": N}; an
        # explicit mesh_axes wins.  Degrades shrink-data-first with a
        # WARN on hosts with fewer devices (resolve_mesh).
        self.tp = int(tp)
        self.dp = int(dp)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_id = int(eos_id)
        self.model_uri = model_uri
        self.seed = int(seed)
        self.engine: Optional[PagedEngine] = None
        self._prom_bridge = None
        self._loop_thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stop = False
        # drain/handoff (r12): set by drain() so the exiting decode loop
        # leaves the engine alone (drain serializes the live streams;
        # the loop's usual close() would error them out uselessly first)
        self._draining = False
        self._load_lock = threading.Lock()
        self._counter = 0
        self._counter_lock = threading.Lock()
        # fleet telemetry plane (r20): per-replica sample ring, fed from
        # the decode loop's throttled collect hook; None when
        # SELDON_TPU_TELEMETRY=0 (no ring, no /debug/telemetry route)
        self._telemetry_ring = None
        # per-request cost ledger handoff: predict() leaves the request's
        # cost totals here and the dispatcher's get_custom_tags() call
        # (same thread, immediately after predict) picks them up via
        # tags() — thread-local because dispatch threads run concurrently
        self._request_cost = threading.local()

    def load(self) -> None:
        # IDEMPOTENT, and it must be: the executor calls load() on graph
        # build while lazy predict paths may already have loaded — a
        # second load would replace self.engine and start a SECOND
        # decode-loop thread, and both threads (the orphaned one reads
        # self.engine dynamically) would step ONE engine concurrently,
        # racing the donated pool buffers ("Array has been deleted")
        with self._load_lock:
            if self.engine is not None:
                return
            import jax.numpy as jnp

            from seldon_core_tpu.models.generate import load_lm_params

            params = load_lm_params(self.model_uri, self.config, self.seed)
            from seldon_core_tpu.parallel.mesh import mesh_from_axes

            mesh = mesh_from_axes(self.mesh_axes)
            # multi-LoRA: the deployment's adapter catalogue registers
            # into the process weight registry (loaders only — cold
            # adapters materialise on first selection, budget-priced),
            # and the engine resolves names through it at submit
            registry = self._register_adapters()
            # tp/dp passed THROUGH so the engine resolves the knobs
            # exactly once: an explicit tp=1/dp=1 here must force the
            # axis off even with SELDON_TPU_TP / SELDON_TPU_DP
            # exported (mesh_axes still wins)
            engine = PagedEngine(
                params, dtype=jnp.bfloat16, mesh=mesh, tp=self.tp or None,
                dp=self.dp or None,
                max_adapters=self.max_adapters, lora_rank=self.lora_rank,
                weight_registry=registry,
                **self.config, **self.engine_config,
            )
            # canonical seldon_tpu_engine_* metrics on the process
            # registry (the gateway's /metrics endpoint serves it);
            # collected from the decode loop.  SELDON_TPU_PROM_BRIDGE=0
            # opts out; a missing prometheus_client degrades to none.
            import os as _os

            if _knobs.flag("SELDON_TPU_PROM_BRIDGE"):
                try:
                    from seldon_core_tpu.utils.metrics import (
                        GenerationPrometheusBridge,
                    )

                    # distinct model_name per engine: two StreamingLMs
                    # in one process (multi-model graph, rolling
                    # re-apply overlap) must not merge into one
                    # timeseries — gauges would flap between engines
                    # and the model_name-keyed dashboards would group
                    # everything under ""
                    global _BRIDGE_SEQ
                    with _BRIDGE_SEQ_LOCK:
                        seq = _BRIDGE_SEQ
                        _BRIDGE_SEQ += 1
                    self._prom_bridge = GenerationPrometheusBridge(
                        engine, model_name=f"streaminglm-{seq}",
                    )
                except Exception:  # noqa: BLE001 — metrics never block serving
                    logger.exception("prometheus bridge unavailable")
            if _telemetry.telemetry_enabled():
                self._telemetry_ring = _telemetry.TelemetryRing(
                    capacity=int(
                        _knobs.raw("SELDON_TPU_TELEMETRY_RING", "256") or 256
                    ),
                )
            # drain/handoff replay (r12): a journal left by a drained
            # predecessor (SIGTERM → drain → exit; the supervisor keeps
            # the path stable across respawns) re-submits its live
            # streams BEFORE the decode loop starts — by first chunk the
            # respawned engine is already re-deriving, and the prompts'
            # prefix pages re-enter the cache where the original
            # callers' retries find them warm.  Unary replay: the
            # original streaming consumers died with the old process.
            journal = _knobs.raw("SELDON_TPU_DRAIN_JOURNAL", "")
            if journal and _os.path.exists(journal):
                try:
                    import json as _json

                    with open(journal) as f:
                        entries = [
                            _json.loads(line)
                            for line in f if line.strip()
                        ]
                    _os.unlink(journal)  # consumed: never replay twice
                    if entries:
                        replayed = engine.replay(entries, stream_tokens=False)
                        logger.info(
                            "drain journal %s: replayed %d/%d streams",
                            journal, len(replayed), len(entries),
                        )
                except Exception:  # noqa: BLE001 — a corrupt journal
                    # must never block serving; the streams it described
                    # are re-derived by caller retries instead
                    logger.exception("drain-journal replay failed (%s)", journal)
            self._loop_thread = threading.Thread(
                target=self._loop, name="streaminglm-decode", daemon=True
            )
            # publish the engine only after full construction; the loop
            # thread reads self.engine
            self.engine = engine
            self._loop_thread.start()

    def _loop(self) -> None:
        import time as _time

        last_collect = 0.0

        def collect(min_interval_s: float) -> None:
            # throttled INSIDE the drain loop too: under sustained load
            # has_work() never goes false, and metrics that only update
            # at idle would freeze during exactly the backlog the
            # queue-depth alert exists for
            nonlocal last_collect
            if self._prom_bridge is None and self._telemetry_ring is None:
                return
            now = _time.monotonic()
            if now - last_collect >= min_interval_s:
                last_collect = now
                if self._prom_bridge is not None:
                    self._prom_bridge.collect()  # internally exception-safe
                if self._telemetry_ring is not None:
                    try:
                        self._telemetry_ring.sample_engine(self.engine)
                    except Exception:  # noqa: BLE001 — telemetry never
                        # blocks serving
                        logger.exception("telemetry sample failed")

        while not self._stop:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            try:
                while self.engine.has_work():
                    if self._stop:
                        break
                    self.engine.step()
                    collect(2.0)
            except Exception as exc:  # surface to all waiters, don't die silently
                self.engine.fail_all(exc)
            collect(0.5)
        # loop stopped: nothing will ever step streams again — reject
        # future submits and unblock every current waiter.  EXCEPT when
        # a drain is in progress: drain() owns the live streams (it
        # journals them for the respawned engine before erroring the
        # waiters with DRAINING), so closing here would destroy the
        # handoff payload.
        if self.engine is not None and not self._draining:
            self.engine.close(
                MicroserviceError("component shut down", status_code=503,
                                  reason="SHUTTING_DOWN")
            )

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()

    def drain(self, journal_path: Optional[str] = None,
              timeout_s: float = 30.0) -> List[Dict[str, Any]]:
        """Drain-then-exit (r12): stop the decode loop at the next chunk
        boundary, journal every live stream's re-derivation recipe, and
        error their local waiters with a clean 503 ``DRAINING``.  The
        journal is written (JSONL, atomic rename) to ``journal_path`` or
        ``SELDON_TPU_DRAIN_JOURNAL`` — the path the supervisor pins per
        worker, so the respawned process replays it on load.  Wired to
        SIGTERM by the microservice runtime; idempotent and safe on a
        never-loaded component (returns [])."""
        import os as _os

        path = journal_path if journal_path is not None else \
            _knobs.raw("SELDON_TPU_DRAIN_JOURNAL", "")
        if self.engine is None:
            return []
        self._quiesce_loop(timeout_s)
        # SIGTERM-with-evacuation (r17): with a peer endpoint
        # configured, live mid-decode streams migrate THERE first —
        # their KV pages, cursors and RNG state resume on the peer at
        # the exact next token instead of re-deriving from scratch.
        # Export or ship failures fall back to ordinary journal
        # entries, so the journal remains the safety net it was in r12.
        entries: List[Dict[str, Any]] = []
        peer = _knobs.raw("SELDON_TPU_EVACUATE_TO", "") or ""
        if peer:
            entries.extend(self._evacuate_remote(peer))
        entries.extend(self.engine.drain())
        if path and entries:
            try:
                import json as _json

                tmp = f"{path}.tmp"
                with open(tmp, "w") as f:
                    for e in entries:
                        f.write(_json.dumps(e) + "\n")
                _os.replace(tmp, path)  # atomic: a respawn never reads half
                logger.info(
                    "drained %d live streams to %s", len(entries), path
                )
            except OSError:
                logger.exception("drain journal write failed (%s)", path)
        return entries

    def _quiesce_loop(self, timeout_s: float = 30.0) -> None:
        """Stop the decode loop at the next chunk boundary (drain and
        evacuation both require no chunk in flight — neither may
        serialize state a device call is still mutating)."""
        self._draining = True
        self._stop = True
        self._wake.set()
        if self._loop_thread is not None and self._loop_thread.is_alive():
            self._loop_thread.join(timeout=timeout_s)
            if self._loop_thread.is_alive():
                logger.error(
                    "decode loop still running after %.0fs drain wait — "
                    "journaling anyway (chunk results for this wave may "
                    "be lost, re-derivation covers them)", timeout_s,
                )

    def evacuate(
        self,
        peers: Sequence[Any],
        journal_path: Optional[str] = None,
        timeout_s: float = 30.0,
    ) -> Dict[str, Any]:
        """In-process live evacuation (r17): quiesce the decode loop,
        live-migrate every exportable stream to a healthy peer
        (priority-ordered, priced by the PR 13 cost model —
        models/disagg.evacuate_streams), journal the rest, and close
        this engine.  ``peers`` are :class:`PagedEngine`s or components
        exposing ``.engine``.  Streaming consumers keep their token
        queues across the move — zero token loss."""
        if self.engine is None:
            return {"migrated": 0, "journaled": 0, "failed": 0}
        from seldon_core_tpu.models.disagg import evacuate_streams

        self._quiesce_loop(timeout_s)
        engines = [getattr(p, "engine", None) or p for p in peers]
        summary = evacuate_streams(self.engine, engines)
        for p in peers:
            wake = getattr(p, "_wake", None)
            if wake is not None:
                wake.set()  # adopted streams resume without the 0.5s poll
        entries = list(summary.pop("journal", []))
        entries.extend(self.engine.drain())
        path = journal_path if journal_path is not None else \
            _knobs.raw("SELDON_TPU_DRAIN_JOURNAL", "")
        if path and entries:
            try:
                import json as _json
                import os as _os

                tmp = f"{path}.tmp"
                with open(tmp, "w") as f:
                    for e in entries:
                        f.write(_json.dumps(e) + "\n")
                _os.replace(tmp, path)
            except OSError:
                logger.exception("evacuation journal write failed (%s)", path)
        summary["journaled"] = len(entries)
        logger.info(
            "evacuation: %d stream(s) live-migrated, %d journaled, "
            "%d failed", summary.get("migrated", 0), len(entries),
            summary.get("failed", 0),
        )
        return summary

    def _evacuate_remote(self, endpoint: str) -> List[Dict[str, Any]]:
        """Ship this engine's exportable streams to ``endpoint`` as SRT1
        migration containers (the DCN lane: one transport-client call
        per stream, metered as ``method="migrate"`` hops).  Returns
        journal entries for every stream that could NOT be shipped;
        shipped streams' local waiters resolve 503 ``MIGRATING`` (their
        state lives on the peer now — upstream retries land there).

        Semantics of the DCN lane, honestly: the zero-token-loss
        guarantee belongs to the IN-PROCESS adoption lane (the consumer
        keeps its token queue).  Across processes the original
        consumer's connection dies with this process; what shipping the
        KV buys is (a) the stream completes on the peer instead of
        being lost, and (b) its prompt's prefix pages register into the
        peer's cache at import — a caller retry against the peer
        re-prefills only the suffix instead of paying the full prompt
        FLOPs a journal replay would."""
        import asyncio
        import time as _time

        from seldon_core_tpu.codec.bufview import pack_kv_migration
        from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
        from seldon_core_tpu.engine.transport import (
            GrpcClient,
            RestClient,
            migration_hop,
        )
        from seldon_core_tpu.models.disagg import migration_journal_entry
        from seldon_core_tpu.runtime.message import InternalMessage

        exported = self.engine.migrate_export()
        if not exported:
            return []
        scheme, sep, rest = endpoint.partition("://")
        if not sep:
            scheme, rest = "grpc", endpoint
        host, _, port = rest.partition(":")
        spec = UnitSpec(
            name=f"evacuate@{rest}",
            endpoint=Endpoint(
                host=host or "localhost", port=int(port or 9000),
                transport="REST" if scheme == "rest" else "GRPC",
            ),
        )
        client = RestClient(spec) if scheme == "rest" else GrpcClient(spec)
        loop = asyncio.new_event_loop()
        fallback: List[Dict[str, Any]] = []
        migrated = 0
        err = MicroserviceError(
            "stream live-migrated to a peer engine during evacuation",
            status_code=503, reason="MIGRATING",
        )
        try:
            # priority-ordered: the most important streams get the
            # evacuation window's budget first
            for payload, stream in sorted(
                exported, key=lambda ps: -ps[0]["priority"]
            ):
                try:
                    buf = pack_kv_migration(payload)
                    with migration_hop("streaminglm-evacuate", "dcn") as hop:
                        if hop is not None:
                            hop.request_bytes = len(buf)
                        msg = InternalMessage(
                            payload=np.frombuffer(buf, np.uint8)[None, :]
                        )
                        msg.meta.tags["kv_migration"] = 1
                        loop.run_until_complete(client.transform_input(msg))
                    migrated += 1
                except Exception:  # noqa: BLE001 — ship failure falls back
                    # to the journal; evacuation must not lose the recipe
                    logger.exception(
                        "migration ship failed for req %s — journaling",
                        payload.get("req_id"),
                    )
                    fallback.append(migration_journal_entry(payload))
                self.engine.fail_stream(stream, err)
        finally:
            try:
                loop.run_until_complete(client.close())
            except Exception:  # noqa: BLE001 — client teardown is
                # best-effort during process exit
                pass
            loop.close()
        logger.info(
            "remote evacuation to %s: %d migrated, %d journaled",
            endpoint, migrated, len(fallback),
        )
        return fallback

    def _register_adapters(self):
        """Register the deployment's adapter catalogue in the process
        weight registry (called from load(), before the engine exists).
        Returns the registry the engine resolves names through, or
        None when multi-LoRA is off entirely."""
        if not (self.adapters or self.max_adapters):
            return None
        from seldon_core_tpu.models.registry import get_registry
        from seldon_core_tpu.ops.lora import target_dims

        registry = get_registry()
        dims = target_dims(self.config["d_model"])
        hint = 4 * self.config["num_layers"] * sum(
            (d_in + d_out) * self.lora_rank for d_in, d_out in dims.values()
        )
        for name, spec in self.adapters.items():
            registry.register(
                name, self._adapter_loader(name, spec), bytes_hint=hint,
            )
        return registry

    def _adapter_loader(self, name: str, spec: Any):
        """One adapter's loader closure: ``{"seed": n}`` builds
        deterministic synthetic factors (bench/tests — deterministic so
        drain-replay and disaggregated workers re-derive identical
        weights), ``{"uri": ...}`` overlays a flax msgpack checkpoint
        on the factor template, and a raw ``{target: (A, B)}`` dict
        passes through (in-process composition)."""
        cfg = dict(self.config)
        rank = self.lora_rank

        def loader():
            from seldon_core_tpu.ops.lora import (
                LORA_TARGETS,
                make_lora_params,
            )

            if isinstance(spec, dict) and any(
                t in spec for t in LORA_TARGETS
            ):
                return spec
            if isinstance(spec, dict) and "uri" in spec:
                from flax import serialization

                from seldon_core_tpu.utils import storage

                template = make_lora_params(
                    0, num_layers=cfg["num_layers"], d_model=cfg["d_model"],
                    rank=rank,
                )
                with open(storage.download(spec["uri"]), "rb") as f:
                    return serialization.from_bytes(template, f.read())
            seed = int(spec.get("seed", 0)) if isinstance(spec, dict) else int(spec)
            alpha = (
                float(spec.get("alpha", rank)) if isinstance(spec, dict)
                else float(rank)
            )
            return make_lora_params(
                seed, num_layers=cfg["num_layers"], d_model=cfg["d_model"],
                rank=rank, alpha=alpha,
            )

        return loader

    @staticmethod
    def _request_adapter(tags) -> Optional[str]:
        """The per-request adapter selection: ``meta.tags.adapter``
        (the ``X-Seldon-Adapter`` header lands here at every ingress;
        an explicit body tag wins).  Empty/None = base model.  Tag and
        header normalize through ONE rule, so both carriers always
        resolve one adapter to one table key."""
        from seldon_core_tpu.utils.deadlines import normalize_adapter

        return normalize_adapter(tags.get("adapter"))

    def _request_seed(self, tags, meta) -> int:
        """The per-request sampling seed rule shared by every serving
        front (unary, streaming, disaggregated): explicit ``seed`` tag
        wins, else the request puid hashes deterministically (a retried
        request reproduces its continuation), else a per-process
        counter keeps distinct requests actually sampling."""
        if "seed" in tags:
            return int(tags["seed"])
        puid = meta.get("puid", "")
        if puid:
            import zlib

            return zlib.crc32(puid.encode())
        with self._counter_lock:
            self._counter += 1
            return self._counter

    @staticmethod
    def _slo_terms(tags) -> Tuple[int, Optional[float]]:
        """Per-request SLO terms: the ``priority`` tag (higher wins,
        clamped like the ingress header — an unauthenticated tag must
        not be an unbounded preemption weapon) and the TIGHTEST of the
        ``deadline_at_monotonic`` tag (absolute expiry the in-process
        streaming lanes mint at ingress), the ``deadline_ms`` tag
        (relative, minted here), and the ambient transport budget
        (utils/deadlines contextvar — run_dispatch copies contextvars
        onto this thread, the same hand-off the trace context rides),
        as an absolute monotonic expiry."""
        import time as _time

        from seldon_core_tpu.utils import deadlines as _deadlines

        try:
            priority = _deadlines.clamp_priority(
                int(float(tags.get("priority", 0)))
            )
        except (TypeError, ValueError):
            priority = 0
        deadline = None
        raw_abs = tags.get("deadline_at_monotonic")
        if raw_abs is not None:
            try:
                deadline = float(raw_abs)
            except (TypeError, ValueError):
                deadline = None
        raw = tags.get("deadline_ms")
        if raw is not None:
            try:
                rel = _time.monotonic() + max(0.0, float(raw)) / 1000.0
                deadline = rel if deadline is None else min(deadline, rel)
            except (TypeError, ValueError):
                pass
        ambient = _deadlines.current_deadline()
        if ambient is not None:
            deadline = (
                ambient.expires_at if deadline is None
                else min(deadline, ambient.expires_at)
            )
        return priority, deadline

    def _accept_migration(self, X) -> np.ndarray:
        """Migration ingress (r17): a peer evacuating its streams POSTs
        each one as a uint8 SRT1 migration container (CRC-checked,
        ``transport.corrupt`` chaos applies); the stream resumes
        decoding HERE at the exact next token.  Returns a 1x1 ack row
        carrying the resumed stream's req id — the sender only needs
        the admission to have succeeded (the original consumers retry
        against this replica through the normal routing layer)."""
        from seldon_core_tpu.codec.bufview import unpack_kv_migration
        from seldon_core_tpu.engine.transport import migration_hop

        buf = np.ascontiguousarray(
            np.asarray(X, np.uint8).reshape(-1)
        ).tobytes()
        buf = _faults.corrupt_bytes("transport.corrupt", buf)
        with migration_hop("streaminglm-ingress", "dcn") as hop:
            if hop is not None:
                hop.request_bytes = len(buf)
            try:
                payload = unpack_kv_migration(buf)
            except Exception as exc:
                raise MicroserviceError(
                    f"malformed migration container: {exc}",
                    status_code=400, reason="BAD_MIGRATION_PAYLOAD",
                ) from exc
            stream = self.engine.migrate_import(payload, stream_tokens=False)
        self._wake.set()
        return np.asarray([[stream.req_id]], np.int32)

    def _capture_model_config(self) -> Dict[str, Any]:
        """The StreamingLM ctor kwargs a replay needs to rebuild THIS
        model (tools/seldon_replay.py): architecture, engine shape and
        numeric regime.  Runtime knobs travel separately in the
        capture's knob snapshot — this is only what the constructor
        pins.  Every value must survive the container's JSON meta
        frame, so non-serializable entries are dropped (a replay of
        such a deployment reconstructs them by hand)."""
        import json as _json

        eng = self.engine_config
        cfg = {
            **self.config,
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "eos_id": self.eos_id,
            "model_uri": self.model_uri,
            "seed": self.seed,
            "page_size": eng["page_size"],
            "num_pages": int(eng["num_pages"] or 0),
            "max_slots": eng["max_slots"],
            "steps_per_call": eng["steps_per_call"],
            "max_steps_per_call": eng["max_steps_per_call"],
            "quantize": eng["quantize"] or "",
            "precision": eng["precision"] or "",
            "speculative": eng["speculative"],
            "prefix_cache": eng["prefix_cache"],
            "max_queue": eng["max_queue"],
            "chunk_token_budget": eng["chunk_token_budget"],
            "mesh_axes": self.mesh_axes,
            "tp": self.tp,
            "dp": self.dp,
            "max_adapters": self.max_adapters,
            "lora_rank": self.lora_rank,
            "adapters": self.adapters,
        }
        out = {}
        for k, v in cfg.items():
            try:
                _json.dumps(v)
            except (TypeError, ValueError):
                continue
            out[k] = v
        return out

    def _maybe_capture(self, streams, *, tags, meta, request_seed,
                       status="ok", reason="", tokens=None) -> None:
        """Per-request black-box write (r21): evaluate the trigger
        matrix for the request's first stream and, when it fires,
        store the capture container.  Multi-row requests capture row 0
        — replay re-submits the whole request, so one container
        recovers every row.  Contained: forensics never breaks
        serving."""
        engine = self.engine
        if engine is None or not engine._capture_enabled or not streams:
            return
        try:
            stream = streams[0]
            puid = str(
                meta.get("puid", "") or stream.puid
                or stream.trace_id or f"req-{stream.req_id}"
            )
            trigger = engine.capture_trigger(
                puid, stream.error if status != "ok" else None,
            )
            if trigger is None and status != "ok":
                trigger = "error"  # raised before/around submit
            if trigger is None:
                return
            deadline_remaining_ms = None
            if stream.deadline is not None:
                import time as _time

                deadline_remaining_ms = max(
                    0.0, (stream.deadline - _time.monotonic()) * 1000.0
                )
            engine.capture_request(
                stream, puid=puid, trigger=trigger, status=status,
                reason=reason, tokens=tokens,
                extra={
                    "request_seed": int(request_seed),
                    "model": self._capture_model_config(),
                    "tags": {
                        k: v for k, v in tags.items()
                        if isinstance(v, (str, int, float, bool))
                    },
                    "rows": len(streams),
                    "deadline_remaining_ms": deadline_remaining_ms,
                },
            )
        except Exception:  # noqa: BLE001 — forensics must not break serving
            logger.exception("request capture failed")

    def predict(self, X, names, meta=None):
        if self.engine is None:
            self.load()  # idempotent + internally locked
        meta = meta or {}
        tags = meta.get("tags", {})
        if tags.get("kv_migration"):
            return self._accept_migration(X)
        max_new = int(tags.get("max_new_tokens", self.max_new_tokens))
        temperature = float(tags.get("temperature", self.temperature))
        top_k = int(tags.get("top_k", self.top_k))
        # sampling must actually sample across requests unless pinned:
        # tag override > puid > per-process counter (GenerativeLM's rule)
        request_seed = self._request_seed(tags, meta)
        priority, deadline = self._slo_terms(tags)
        adapter = self._request_adapter(tags)
        X = np.atleast_2d(np.asarray(X, np.int32))
        streams = []
        try:
            for i, row in enumerate(X):
                # multiplicative row spread: (seed ^ c) + i style
                # additive mixing collides across neighbouring requests
                streams.append(self.engine.submit(
                    row, max_new_tokens=max_new, temperature=temperature,
                    top_k=top_k, eos_id=self.eos_id,
                    seed=self.seed ^ (request_seed * 1000003 + i),
                    priority=priority, deadline=deadline, adapter=adapter,
                    puid=str(meta.get("puid", "")),
                ))
            self._wake.set()
            for stream in streams:
                stream.event.wait()
                if stream.error:
                    raise stream.error
            if self.engine._telemetry_enabled:
                # cost ledger handoff: the dispatcher reads tags() on
                # THIS thread right after predict returns, so the
                # request's cost totals ride meta.tags.cost on the
                # response the caller actually sees
                self._request_cost.value = {
                    "page_seconds": round(
                        sum(s.cost_page_s for s in streams), 6
                    ),
                    "prefill_tokens": sum(
                        s.cost_prefill_tokens for s in streams
                    ),
                    "decode_tokens": sum(
                        s.cost_decode_tokens for s in streams
                    ),
                    "preemptions": sum(s.cost_preempts for s in streams),
                    "restores": sum(s.cost_restores for s in streams),
                    "adapter": adapter or "base",
                }
            result = np.stack([s.result for s in streams])
            self._maybe_capture(
                streams, tags=tags, meta=meta, request_seed=request_seed,
                status="ok", tokens=streams[0].result,
            )
            return result
        except BaseException as exc:
            # one row shed/expired/errored: the siblings must not keep
            # decoding unread — they hold slots and KV pages exactly
            # when the engine is overloaded enough to shed
            for s in streams:
                if s.result is None and s.error is None:
                    self.engine.cancel(s)
            self._maybe_capture(
                streams, tags=tags, meta=meta, request_seed=request_seed,
                status="error", reason=repr(exc),
            )
            raise

    def predict_stream(self, X, names=None, meta=None):
        """Token streaming for ONE prompt: a generator yielding int32
        arrays of newly decoded tokens as the engine emits them (the
        serving UX modern generation stacks expose; the reference
        predates it).  Same per-request overrides as predict; greedy
        re-runs after an eviction resume exactly where the consumer
        left off (deterministic seeds + the streamed cursor).
        """
        if self.engine is None:
            self.load()  # idempotent + internally locked
        meta = meta or {}
        tags = meta.get("tags", {})
        max_new = int(tags.get("max_new_tokens", self.max_new_tokens))
        temperature = float(tags.get("temperature", self.temperature))
        top_k = int(tags.get("top_k", self.top_k))
        # same seed rule as predict: tag override > puid > counter, so a
        # streamed request samples identically to the unary predict of
        # the same request (and a retried stream with the same puid
        # reproduces its continuation)
        request_seed = self._request_seed(tags, meta)
        X = np.atleast_2d(np.asarray(X, np.int32))
        if X.shape[0] != 1:
            raise MicroserviceError(
                "token streaming serves one prompt per stream; send rows "
                "separately (predict() batches them)",
                status_code=400, reason="BAD_REQUEST",
            )
        priority, deadline = self._slo_terms(tags)
        stream = self.engine.submit(
            X[0], max_new_tokens=max_new, temperature=temperature,
            top_k=top_k, eos_id=self.eos_id,
            seed=self.seed ^ (request_seed * 1000003),
            stream_tokens=True,
            priority=priority, deadline=deadline,
            adapter=self._request_adapter(tags),
            puid=str(meta.get("puid", "")),
        )
        self._wake.set()
        try:
            while True:
                got = stream.token_queue.get()
                if got is None:
                    break
                yield np.asarray(got, np.int32)
            if stream.error:
                err = stream.error
                self._maybe_capture(
                    [stream], tags=tags, meta=meta,
                    request_seed=request_seed, status="error",
                    reason=repr(err),
                )
                raise err
            # normal completion (a mid-stream disconnect skips capture:
            # the consumer leaving is not a serving incident)
            self._maybe_capture(
                [stream], tags=tags, meta=meta,
                request_seed=request_seed, status="ok",
            )
        finally:
            # consumer gone (disconnect/cancel) or done: an abandoned
            # stream must not keep decoding into an unread queue,
            # holding a slot and pages against live requests
            self.engine.cancel(stream)

    def tags(self):
        """Response meta tags: the LAST predict's cost-ledger totals on
        this dispatch thread (dispatch calls get_custom_tags right after
        predict on the same thread).  Pop-once so a later request that
        fails before submit cannot inherit a stale ledger."""
        cost = getattr(self._request_cost, "value", None)
        self._request_cost.value = None
        return {"cost": cost} if cost else {}

    def telemetry_snapshot(self, window_s: float = 0.0):
        """The versioned per-replica telemetry payload.  Takes one fresh
        engine sample first: pollers arriving between decode-loop
        collect ticks (or while the engine idles) must still see current
        queue depth / residency, not the last busy-period point."""
        if self._telemetry_ring is None:
            return None
        if self.engine is not None:
            try:
                self._telemetry_ring.sample_engine(self.engine)
            except Exception:  # noqa: BLE001 — serve what the ring has
                logger.exception("telemetry sample failed")
        return self._telemetry_ring.snapshot(window_s)

    def custom_routes(self):
        """``GET /debug/telemetry`` on the worker's own REST surface —
        what the fleet aggregator polls.  No ring (telemetry off) means
        no route: the =0 lane serves the exact pre-telemetry routes."""
        if self._telemetry_ring is None:
            return {}

        def debug_telemetry(request):
            try:
                window_s = float(request.query.get("window", "0") or 0.0)
            except (ValueError, AttributeError):
                window_s = 0.0
            return self.telemetry_snapshot(window_s)

        return {"/debug/telemetry": debug_telemetry}

    def metrics(self):
        """Paged-engine health for the dashboards.  All GAUGEs:
        metrics() is collected after every request, so cumulative values
        exported as COUNTERs would be inc()'d repeatedly (same
        convention as jaxserver/SpeculativeLM)."""
        if self.engine is None:
            return []
        s = self.engine.engine_stats()
        total = max(1, s["pool_pages_total"])
        return [
            {"type": "GAUGE", "key": "paged_active_slots", "value": s["active_slots"]},
            {"type": "GAUGE", "key": "paged_queued_streams", "value": s["queued_streams"]},
            {"type": "GAUGE", "key": "paged_pool_utilization", "value": s["pool_pages_used"] / total},
            {"type": "GAUGE", "key": "paged_evictions", "value": s["evictions"]},
            {"type": "GAUGE", "key": "paged_stall_events", "value": s["stalls"]},
            {"type": "GAUGE", "key": "paged_chunks", "value": s["chunks"]},
            {"type": "GAUGE", "key": "paged_tokens_emitted", "value": s["tokens"]},
            {"type": "GAUGE", "key": "paged_streams_completed", "value": s["completed"]},
            {"type": "GAUGE", "key": "paged_prefix_hit_rate",
             "value": s["prefix_hits"]
             / max(1, s["prefix_hits"] + s["prefix_misses"])},
            {"type": "GAUGE", "key": "paged_prefix_pages_cached",
             "value": s["prefix_pages_cached"]},
            {"type": "GAUGE", "key": "paged_prefix_tokens_saved",
             "value": s["prefix_tokens_saved"]},
            {"type": "GAUGE", "key": "paged_tp_degree",
             "value": s["tp_degree"]},
            {"type": "GAUGE", "key": "paged_dp_degree",
             "value": s["dp_degree"]},
            {"type": "GAUGE", "key": "paged_adapters_resident",
             "value": s["adapters_resident"]},
        ] + (
            [
                {"type": "GAUGE", "key": "speculative_acceptance_rate",
                 "value": s["spec_accepted"] / max(1, s["spec_drafted"])},
                {"type": "GAUGE", "key": "speculative_rounds",
                 "value": s["chunks"]},
            ]
            if self.engine.speculative is not None else []
        )

    def class_names(self):
        return []
