"""jaxserver — the flagship prepackaged TPU inference server.

The TPU-native answer to the reference's prepackaged servers
(reference: servers/sklearnserver/sklearnserver/SKLearnServer.py:15-44
pattern: download model -> expose ``SeldonComponent``) and its
GPU-proxy path (reference: integrations/nvidia-inference-server/
TRTProxy.py:50-81), collapsed into one in-process component:

* the model is a flax module (builtin registry: resnet18/34/50/101/152,
  vit_tiny/base16/large16, transformer encoder/LM,
  mlp, tiny test configs — or any dotted ``pkg.module.fn`` returning a
  module) jit-compiled to XLA at ``load()``;
* parameters load from ``model_uri`` (flax msgpack via the storage
  downloader, or an orbax checkpoint dir) and are pinned in HBM once,
  optionally sharded over a device mesh;
* compute runs in ``bfloat16`` by default (MXU-native), activations
  cast on device;
* requests flow through the dynamic batcher: concurrent requests
  coalesce into padded-bucket device calls, every bucket pre-compiled
  and warmed at load time so no request ever pays a trace.

Declaratively selected with ``implementation: JAX_SERVER`` in a graph
spec, the way the reference selects SKLEARN_SERVER et al.
(reference: proto/seldon_deployment.proto:102-113).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from seldon_core_tpu.batching.batcher import DynamicBatcher, MultiSignatureBatcher
from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent, gauge_metric

logger = logging.getLogger(__name__)


def _compute_dtype(name: str):
    import jax.numpy as jnp

    try:
        return {"bfloat16": jnp.bfloat16, "float32": jnp.float32, "float16": jnp.float16}[name]
    except KeyError:
        raise MicroserviceError(
            f"unknown dtype {name!r} (supported: bfloat16, float32, float16)",
            status_code=400,
            reason="BAD_DTYPE",
        ) from None


def _model_registry() -> Dict[str, Callable[..., Tuple[Any, Tuple[int, ...]]]]:
    """name -> factory(num_classes, dtype) -> (module, example_input_shape)."""
    from seldon_core_tpu.models import mlp, resnet, vit

    def entry(cls, shape):
        def factory(num_classes: int, dtype, **kw):
            return cls(num_classes=num_classes, dtype=dtype, **kw), shape

        return factory

    from seldon_core_tpu.models import transformer

    img = resnet.IMAGENET_INPUT_SHAPE
    return {
        "resnet18": entry(resnet.ResNet18, img),
        "resnet34": entry(resnet.ResNet34, img),
        "resnet50": entry(resnet.ResNet50, img),
        "resnet101": entry(resnet.ResNet101, img),
        "resnet152": entry(resnet.ResNet152, img),
        "resnet_tiny": entry(resnet.ResNetTiny, (32, 32, 3)),
        "mlp": entry(mlp.MLPClassifier, (4,)),
        "vit_tiny": entry(_with_attention(vit.ViTTiny), (32, 32, 3)),
        "vit_base16": entry(_with_attention(vit.ViTBase16), img),
        "vit_large16": entry(_with_attention(vit.ViTLarge16), img),
        # long-context families: input is a token-id sequence (int32);
        # input_shape must be given explicitly (the served context length).
        # model_kwargs may name the attention impl: {"attention": "flash"}
        # selects the pallas blockwise kernel, "plain" the einsum path
        # (ring attention needs a mesh, so it stays programmatic).
        "transformer_encoder": entry(
            lambda num_classes, dtype, **kw: transformer.TransformerEncoder(
                num_classes=num_classes, dtype=dtype, **_resolve_attention(kw)
            ),
            None,
        ),
        "transformer_lm": entry(
            lambda num_classes, dtype, **kw: transformer.TransformerLM(
                dtype=dtype, **_resolve_attention(kw)
            ),
            None,
        ),
        # detection family: output is (batch, top_k, 6) decoded boxes
        # [x1,y1,x2,y2,score,cls] — decode (peak-NMS + lax.top_k) fuses
        # into the served XLA program; model_kwargs: backbone, top_k,
        # score_threshold, input_size, head_dim
        "detector_tiny": _detector_entry("resnet_tiny", 64),
        "detector_resnet18": _detector_entry("resnet18", 512),
        "detector_resnet50": _detector_entry("resnet50", 512),
    }


def _detector_entry(backbone: str, default_size: int):
    from seldon_core_tpu.models.detection import make_detector

    def factory(num_classes: int, dtype, **kw):
        kw.setdefault("backbone", backbone)
        kw.setdefault("input_size", default_size)
        module, shape = make_detector(num_classes, dtype, **kw)
        return module, shape

    return factory


def _with_attention(cls):
    """Registry factory routing the "attention" model_kwarg for classes
    with a pluggable attn_fn (vit_* share the transformer blocks)."""

    def make(num_classes: int, dtype, **kw):
        return cls(num_classes=num_classes, dtype=dtype, **_resolve_attention(kw))

    return make


def _resolve_attention(kw: Dict[str, Any]) -> Dict[str, Any]:
    """Map a JSON-able {"attention": "flash"|"plain"} kwarg to attn_fn."""
    kw = dict(kw)
    choice = kw.pop("attention", None)
    if choice == "flash":
        from seldon_core_tpu.ops.kernels import flash_attn_fn

        kw["attn_fn"] = flash_attn_fn()
    elif choice not in (None, "plain"):
        raise MicroserviceError(
            f"unknown attention {choice!r} (supported: plain, flash)",
            status_code=400,
            reason="BAD_ATTENTION",
        )
    return kw


class JaxServer(TPUComponent):
    """Serve a flax model jit-compiled to XLA with dynamic batching."""

    accepts_device_arrays = True
    # libtpu is single-process per chip: subprocess replicas of this
    # component would fight over the device (controlplane hpa guard)
    device_exclusive = True

    def __init__(
        self,
        model: str = "mlp",
        model_uri: str = "",
        num_classes: int = 1000,
        dtype: str = "bfloat16",
        max_batch_size: int = 64,
        max_wait_ms: float = 1.0,
        buckets: Optional[Sequence[int]] = None,
        input_shape: Optional[Sequence[int]] = None,
        extra_input_shapes: Optional[Sequence[Sequence[int]]] = None,
        class_names_list: Optional[List[str]] = None,
        softmax_outputs: bool = False,
        top_k: int = 0,
        warmup: bool = True,
        warmup_dtypes: Sequence[str] = ("float32", "uint8"),
        quantize: str = "",
        precision: str = "",
        calibration_batches: int = 4,
        normalize: bool = False,
        normalize_mean: Optional[Sequence[float]] = None,
        normalize_std: Optional[Sequence[float]] = None,
        seed: int = 0,
        mesh: Optional[Any] = None,
        data_axis: str = "data",
        model_kwargs: Optional[Dict[str, Any]] = None,
        pipeline_depth: int = 16,
        finisher_threads: int = 12,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model_name = model
        self.model_uri = model_uri
        self.num_classes = int(num_classes)
        self.dtype_name = dtype
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.buckets = list(buckets) if buckets else None
        self.input_shape = tuple(input_shape) if input_shape else None
        # extra accepted signatures (e.g. several context-length buckets
        # for a served transformer); each gets its own batcher queue and
        # compiled program — see MultiSignatureBatcher
        self.extra_input_shapes = [tuple(s) for s in (extra_input_shapes or [])]
        self._class_names = class_names_list
        self.softmax_outputs = bool(softmax_outputs)
        # top_k > 0: the served program ends in lax.top_k and returns
        # [batch, 2, k] (row 0: class indices, row 1: scores).  The
        # device->host readback and the response payload shrink from
        # num_classes to 2k floats per example — fused on device, so
        # the full logits never leave HBM.
        self.top_k = int(top_k)
        self.warmup = bool(warmup)
        # XLA specialises on input dtype as well as shape: warm every
        # (bucket, dtype) pair clients may send, and canonicalise anything
        # else host-side so a stray float64 tensor payload can never
        # trigger a mid-traffic recompile
        self.warmup_dtypes = tuple(warmup_dtypes)
        # quantize="int8": weight-only quantisation of the loaded
        # checkpoint (ops/surgery.py) — kernels live in HBM as int8,
        # dequant fuses into the consuming matmul/conv inside the jit.
        # precision widens the vocabulary: "int8w" is the same weight-
        # only lane, "w8a8" additionally runs int8×int8 compute on the
        # MXU (ops/w8a8.py) with activation scales calibrated at load.
        from seldon_core_tpu.ops.surgery import (
            quantize_mode_for,
            validate_precision,
            validate_quantize_mode,
        )

        try:
            validate_quantize_mode(quantize)
            validate_precision(precision)
        except ValueError as e:
            raise MicroserviceError(str(e), status_code=400, reason="BAD_QUANTIZE")
        self.precision = precision
        self.quantize = quantize or quantize_mode_for(precision)
        self.calibration_batches = int(calibration_batches)
        self.act_scales_calibrated = 0
        self.quantize_manifest: List[Dict[str, Any]] = []
        # normalize=True: uint8 image batches go through the fused
        # pallas cast+affine kernel (ops.fused_normalize) before the
        # model — one VMEM pass instead of an HBM convert/mul/add chain
        self.normalize = bool(normalize)
        self._norm_mean = tuple(normalize_mean) if normalize_mean else None
        self._norm_std = tuple(normalize_std) if normalize_std else None
        self.seed = int(seed)
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_kwargs = dict(model_kwargs or {})
        # pipeline knobs: in-flight device batches and concurrent
        # device->host readbacks.  Throughput through a high-latency
        # host<->device link is depth x batch / RTT — on the relayed
        # harness, 4 finishers measured 650 img/s and 12 measured
        # ~3000 img/s for the same device work, so depth, not compute,
        # sets serving capacity (see batching/batcher.py pipeline notes)
        self.pipeline_depth = int(pipeline_depth)
        self.finisher_threads = int(finisher_threads)
        self._loaded = False
        self.module = None
        self.variables = None
        self._predict_jit = None
        self.batcher: Optional[DynamicBatcher] = None
        self._load_time_s: Optional[float] = None

    # ----------------------------------------------------------------- load

    def _build_module(self):
        import jax.numpy as jnp

        dtype = _compute_dtype(self.dtype_name)
        registry = _model_registry()
        model_kwargs = dict(self.model_kwargs)
        if self.precision == "w8a8":
            # the knob rides model_kwargs so any registry module with a
            # ``precision`` field (the resnet family) picks it up with
            # zero plumbing; dotted-path factories receive it explicitly
            # below — both paths fail loudly if the model can't take it
            mk_precision = model_kwargs.get("precision")
            if mk_precision not in (None, "w8a8"):
                # a conflicting model_kwargs value must not silently win
                # over the server-level knob: /health/status would
                # report w8a8 while the module computes something else
                raise MicroserviceError(
                    f"precision={self.precision!r} conflicts with "
                    f"model_kwargs precision={mk_precision!r}",
                    status_code=400,
                    reason="BAD_PRECISION",
                )
            model_kwargs["precision"] = "w8a8"
        if self.model_name in registry:
            try:
                module, default_shape = registry[self.model_name](
                    self.num_classes, dtype, **model_kwargs
                )
            except TypeError as e:
                # only claim a precision problem when the TypeError IS
                # about the precision kwarg — any other bad model_kwarg
                # must surface as itself, not send the operator to
                # debug the wrong knob
                if self.precision == "w8a8" and "precision" in str(e):
                    raise MicroserviceError(
                        f"model {self.model_name!r} does not take a "
                        f"precision kwarg (w8a8 is supported by the resnet "
                        f"family and precision-aware custom factories): {e}",
                        status_code=400,
                        reason="BAD_PRECISION",
                    ) from None
                raise
        else:
            # dotted path to a factory: returns module or (module, shape)
            import importlib

            module_name, _, attr = self.model_name.rpartition(".")
            if not module_name:
                raise MicroserviceError(
                    f"unknown model {self.model_name!r}; builtin options: {sorted(registry)}",
                    status_code=400,
                    reason="UNKNOWN_MODEL",
                )
            factory = getattr(importlib.import_module(module_name), attr)
            factory_kwargs = dict(num_classes=self.num_classes, dtype=dtype)
            if self.precision == "w8a8":
                # the knob must reach the factory or fail loudly: a
                # dotted factory that silently ignores it would serve
                # bf16 compute under a w8a8 label — the wrong-lane
                # failure mode the HLO audit exists to prevent
                factory_kwargs["precision"] = "w8a8"
            try:
                built = factory(**factory_kwargs)
            except TypeError as e:
                if self.precision == "w8a8" and "precision" in str(e):
                    raise MicroserviceError(
                        f"model factory {self.model_name!r} does not take a "
                        f"precision kwarg (required for w8a8): {e}",
                        status_code=400,
                        reason="BAD_PRECISION",
                    ) from None
                raise
            module, default_shape = built if isinstance(built, tuple) else (built, None)
        if self.input_shape is None:
            if default_shape is None:
                raise MicroserviceError(
                    f"model {self.model_name!r} needs an explicit input_shape",
                    status_code=400,
                    reason="MISSING_INPUT_SHAPE",
                )
            self.input_shape = tuple(default_shape)
        return module

    def _init_or_load_params(self):
        import jax
        import jax.numpy as jnp

        example = jnp.zeros((1, *self.input_shape), jnp.float32)

        def split_act(template):
            """Detach the act_scales collection from a restore template:
            checkpoints were saved by precision-less modules, so the
            w8a8 scales (calibrated at load, not stored) must not be
            looked up in the checkpoint bytes."""
            from flax.core import unfreeze

            template = dict(unfreeze(template))
            aux = {
                k: template.pop(k) for k in ("act_scales",) if k in template
            }
            return template, aux

        def concrete_aux(aux):
            # eval_shape templates carry ShapeDtypeStructs; scales start
            # at the uncalibrated zero either way
            return {
                k: jax.tree_util.tree_map(
                    lambda s: jnp.zeros(getattr(s, "shape", ()), getattr(s, "dtype", jnp.float32)), v
                )
                for k, v in aux.items()
            }

        if self.model_uri:
            from seldon_core_tpu.utils import storage

            path = storage.download(self.model_uri)
            if os.path.isdir(path) and os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA")):
                import orbax.checkpoint as ocp

                ckptr = ocp.StandardCheckpointer()
                template = jax.eval_shape(lambda: self.module.init(jax.random.key(0), example))
                template, aux = split_act(template)
                variables = ckptr.restore(os.path.abspath(path), template)
                variables = {**dict(variables), **concrete_aux(aux)}
            else:
                # flax msgpack file
                from flax import serialization

                if os.path.isdir(path):
                    candidates = [f for f in os.listdir(path) if f.endswith((".msgpack", ".bin"))]
                    if not candidates:
                        raise MicroserviceError(
                            f"no .msgpack checkpoint under {path}", status_code=500, reason="BAD_CHECKPOINT"
                        )
                    path = os.path.join(path, sorted(candidates)[0])
                template = self.module.init(jax.random.key(0), example)
                template, aux = split_act(template)
                with open(path, "rb") as f:
                    variables = serialization.from_bytes(template, f.read())
                variables = {**dict(variables), **concrete_aux(aux)}
            return variables
        # benchmark / smoke mode: random init
        return self.module.init(jax.random.key(self.seed), example)

    def _pin_params(self, variables):
        """Place parameters in device memory (replicated over the mesh)."""
        import jax

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(self.mesh, P())
            return jax.device_put(variables, replicated)
        return jax.device_put(variables)

    def load(self) -> None:
        if self._loaded:
            return
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        compute_dtype = _compute_dtype(self.dtype_name)
        self.module = self._build_module()
        variables = self._init_or_load_params()

        if self.normalize:
            from seldon_core_tpu.ops.kernels import imagenet_affine

            if self._norm_mean is not None or self._norm_std is not None:
                mean = np.asarray(self._norm_mean or (0.0,), np.float32)
                std = np.asarray(self._norm_std or (1.0,), np.float32)
                # mean/std broadcast together to the channel count so that
                # supplying only one of them still yields per-channel
                # scale/shift (fused_normalize reshapes both to (1,..,C))
                mean, std = np.broadcast_arrays(mean, std)
                norm_scale, norm_shift = 1.0 / (255.0 * std), -mean / std
            else:
                norm_scale, norm_shift = imagenet_affine()

        if self.precision == "w8a8" and self.calibration_batches > 0:
            # static PTQ calibration (Jacob et al. 2018): a few sample
            # batches through the SAME preprocessing the serving path
            # applies fix the per-tensor activation scales the int8
            # programs read.  Runs on the fp tree BEFORE surgery (the
            # capture pass needs plain kernels), host-side batches so
            # no request ever sees an uncalibrated program.
            from seldon_core_tpu.ops.w8a8 import calibrate_act_scales

            crng = np.random.default_rng(self.seed + 101)
            cb = min(8, self.max_batch_size)
            batches = []
            for _ in range(self.calibration_batches):
                img = crng.integers(0, 256, size=(cb, *self.input_shape))
                if self.normalize:
                    x = img.astype(np.float32) * np.asarray(
                        norm_scale, np.float32
                    ) + np.asarray(norm_shift, np.float32)
                else:
                    x = img.astype(np.dtype(self.warmup_dtypes[0]))
                batches.append(jnp.asarray(x))
            variables, self.act_scales_calibrated = calibrate_act_scales(
                self.module, variables, batches
            )
            logger.info(
                "w8a8 calibration: %d activation scales fixed over %d batches",
                self.act_scales_calibrated, len(batches),
            )

        if self.quantize == "int8":
            from seldon_core_tpu.ops.surgery import quantize_params, tree_hbm_bytes

            bytes_fp = tree_hbm_bytes(variables)
            variables, self.quantize_manifest = quantize_params(variables)
            logger.info(
                "int8 surgery: %d kernels quantized, params %.1f MB -> %.1f MB",
                len(self.quantize_manifest),
                bytes_fp / 1e6,
                tree_hbm_bytes(variables) / 1e6,
            )
        self.variables = self._pin_params(variables)

        self._apply_fn = None  # set below; used by loop_forward_rate

        def apply_fn(variables, x):
            if self.quantize == "int8":
                from seldon_core_tpu.ops.surgery import dequantize_params

                # w8a8 dequantises to f32, not the compute dtype: the
                # W8A8 layers RE-quantise the kernels in-graph, and a
                # bf16 intermediate double-rounds — round(bf16(q*s)/s)
                # can flip integers by ±1 vs the at-rest tensor.  The
                # f32 tree is transient (fused into operand reads); the
                # non-quantised layers (stem/head/BN) cast to their own
                # dtype at compute exactly as before.
                dequant_dtype = (
                    jnp.float32 if self.precision == "w8a8" else compute_dtype
                )
                variables = dequantize_params(variables, dequant_dtype)
            if self.normalize and x.dtype == jnp.uint8:
                from seldon_core_tpu.ops.kernels import fused_normalize

                x = fused_normalize(x, norm_scale, norm_shift, out_dtype=compute_dtype)
            y = self.module.apply(variables, x)
            if self.softmax_outputs:
                y = jax.nn.softmax(y, axis=-1)
            if self.top_k:
                values, indices = jax.lax.top_k(y, self.top_k)
                y = jnp.stack([indices.astype(jnp.float32), values], axis=-2)
            return y

        self._apply_fn = apply_fn
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            in_shardings = (NamedSharding(self.mesh, P()), NamedSharding(self.mesh, P(self.data_axis)))
            out_shardings = NamedSharding(self.mesh, P(self.data_axis))
            self._predict_jit = jax.jit(apply_fn, in_shardings=in_shardings, out_shardings=out_shardings)
        else:
            self._predict_jit = jax.jit(apply_fn)

        def device_call(batch: np.ndarray):
            # returns the device array: XLA dispatch is async, and the
            # batcher pipeline overlaps readback with the next batch
            return self._predict_jit(self.variables, jnp.asarray(batch))

        batcher_cls = MultiSignatureBatcher if self.extra_input_shapes else DynamicBatcher
        self.batcher = batcher_cls(
            device_call,
            max_batch_size=self.max_batch_size,
            max_wait_ms=self.max_wait_ms,
            buckets=self.buckets,
            name=f"jaxserver-{self.model_name}",
            pipeline_depth=self.pipeline_depth,
            finisher_threads=self.finisher_threads,
        )
        self.batcher.start()

        if self.warmup:
            # pre-compile every (shape, bucket, dtype) triple so no
            # request pays a trace — over the batcher's NORMALIZED
            # bucket list (it force-appends max_batch_size), not the
            # raw user-supplied one
            for shape in self.accepted_shapes():
                for b in self.batcher.buckets:
                    for dt in self.warmup_dtypes:
                        np.asarray(device_call(np.zeros((b, *shape), np.dtype(dt))))
        self._load_time_s = time.perf_counter() - t0
        self._loaded = True
        logger.info(
            "jaxserver %s loaded in %.2fs (buckets=%s, dtype=%s)",
            self.model_name,
            self._load_time_s,
            self.batcher.buckets,
            self.dtype_name,
        )

    def unload(self) -> None:
        if self.batcher is not None:
            self.batcher.stop()
        self._loaded = False

    # -------------------------------------------------------------- serving

    def accepted_shapes(self) -> List[Tuple[int, ...]]:
        """Input signatures (without batch dim) this server accepts."""
        return [tuple(self.input_shape), *self.extra_input_shapes]

    def _prepare(self, X):
        """Canonicalise dtype and shape.

        Shape precedence: the batch interpretation always wins — an
        array whose *trailing* dims match an accepted signature is
        treated as [batch, *sig] even if its full shape also matches
        another signature (e.g. with signatures (16,) and (16, 16), a
        (16, 16) array is a batch of 16 vectors, never a single
        16x16 example).  Send an explicit leading batch dim of 1 to
        force the single-example reading.
        """
        if not self._loaded:
            self.load()
        arr = np.asarray(X)
        if arr.dtype.name not in self.warmup_dtypes:
            arr = arr.astype(np.dtype(self.warmup_dtypes[0]))
        accepted = self.accepted_shapes()
        squeeze = False
        if tuple(arr.shape[1:]) not in accepted and tuple(arr.shape) in accepted:
            arr = arr[None]  # single example without batch dim
            squeeze = True
        if tuple(arr.shape[1:]) not in accepted and arr.ndim == 2:
            # flat rows [batch, prod(sig)]: the wire-efficient layout the
            # native ingress fast lane speaks — reshape to the first
            # matching signature (same rule as raw_batch_call)
            for sig in accepted:
                if arr.shape[1] == int(np.prod(sig)):
                    arr = arr.reshape((arr.shape[0], *sig))
                    break
        if tuple(arr.shape[1:]) not in accepted:
            shapes = " | ".join("(batch, " + ", ".join(map(str, s)) + ")" for s in accepted)
            raise MicroserviceError(
                f"input shape {tuple(arr.shape)} does not match model input {shapes}",
                status_code=400,
                reason="BAD_INPUT_SHAPE",
            )
        return arr, squeeze

    def predict(self, X, names, meta=None):
        arr, squeeze = self._prepare(X)
        out = self.batcher.submit(arr)
        return out[0] if squeeze else out

    async def predict_async(self, X, names, meta=None):
        """Async fast path: awaits the batch future without pinning a
        dispatch thread — the engine's LocalClient prefers this, so an
        arbitrary number of requests can ride the batcher concurrently."""
        import asyncio

        arr, squeeze = self._prepare(X)
        out = await asyncio.wrap_future(self.batcher.submit_future(arr))
        return out[0] if squeeze else out

    # ---- native fast lane -------------------------------------------------

    def flat_feature_dim(self) -> int:
        """Row width of the flattened input the native ingress sends."""
        if self.input_shape is None:
            self.load()
        return int(np.prod(self.input_shape))

    def flat_out_dim(self) -> int:
        """Row width of the flattened output (2k for fused top-k)."""
        return 2 * self.top_k if self.top_k else self.num_classes

    def raw_batch_call(self, batch2d: np.ndarray) -> np.ndarray:
        """One model call for a C++-coalesced batch:
        [rows, flat] f32|u8 -> [rows, out] f32.

        The C++ ingress owns request decode + coalescing and calls this
        from its batch-worker threads.  The call rides the SAME
        DynamicBatcher pipeline as every other lane — single dispatch
        thread, deep async readback — because concurrent direct jit
        calls from many OS threads measured ~6x SLOWER than one
        dispatcher with pipelined readbacks (thread-contended dispatch
        wedges the host<->device path; the C++ workers just park on
        their batch's future, which is cheap).  A C++-coalesced full
        batch passes through the batcher without re-buffering (it
        already fills the bucket); partial batches get a second
        coalescing window for free.
        """
        import jax.numpy as jnp

        if not self._loaded:
            self.load()
        # dtype-preserving: a uint8 frame decoded in C++ reaches the
        # device as uint8 (its program was warmed); only un-warmed
        # dtypes canonicalise, or the call would trace mid-traffic
        arr = np.asarray(batch2d)
        if arr.dtype.name not in self.warmup_dtypes:
            arr = arr.astype(np.dtype(self.warmup_dtypes[0]))
        arr = arr.reshape((-1, *self.input_shape))
        batcher = self.batcher
        if batcher is None:  # unloaded mid-call: direct jit, no pipeline
            out = np.asarray(self._predict_jit(self.variables, jnp.asarray(arr)))
        else:
            # device errors (XlaRuntimeError etc.) propagate — retrying
            # the batch with direct concurrent jit calls would mask the
            # error AND hit the thread-contended dispatch path
            out = batcher.submit(arr, timeout_s=120.0)
        return np.asarray(out).reshape(arr.shape[0], -1)

    def raw_batch_views(self, views, timeout_s: float = 120.0):
        """Batched submission front for the zero-copy lane: N buffer
        views ``[rows_i, flat]`` stack into ONE contiguous micro-batch
        (single allocation; a lone full view passes through with no
        copy at all), ride the SAME DynamicBatcher pipeline as every
        other lane — one ``jnp.asarray``/``device_put`` per micro-batch
        — and split back into per-view output slices.

        This replaces the per-request proto→dict→numpy round-trip the
        python model path paid: the views are ``np.frombuffer`` windows
        over the ingress byte buffers, so the first copy a request
        payload experiences inside Python is the device staging buffer.
        Capacity/deadline semantics are the batcher's own, unchanged.
        """
        from seldon_core_tpu.codec.bufview import BufferView, stack_views

        if not self._loaded:
            self.load()
        norm = []
        for v in views:
            arr = v.array() if isinstance(v, BufferView) else np.asarray(v)
            if arr.ndim == 1:
                arr = arr[None, :]
            if arr.dtype.name not in self.warmup_dtypes:
                arr = arr.astype(np.dtype(self.warmup_dtypes[0]))
            norm.append(arr.reshape(arr.shape[0], -1))
        if len({a.dtype for a in norm}) > 1:
            # a mixed-dtype wave (f32 + u8 clients in one window) stacks
            # at the canonical dtype rather than failing the whole wave
            canon = np.dtype(self.warmup_dtypes[0])
            norm = [a.astype(canon, copy=False) for a in norm]
        batch, offsets = stack_views(norm, dtype=norm[0].dtype)
        out = np.asarray(self.raw_batch_call(batch))
        return [out[offsets[i]:offsets[i + 1]] for i in range(len(norm))]

    def loop_forward_rate(
        self,
        iters_small: int = 8,
        iters_big: int = 40,
        batch: Optional[int] = None,
        n_resident: int = 4,
        seed: int = 7,
        target_seconds: float = 1.5,
        max_iters: int = 20000,
    ) -> Dict[str, Any]:
        """True device forward rate: N forwards per SINGLE dispatch.

        A ``lax.fori_loop`` over device-resident batches runs the whole
        measurement as one compiled program with one scalar readback, so
        per-dispatch host/link cost (the ~65 ms relay floor in this
        harness, PCIe sync cost on attached hosts) cannot cap the
        number — this is the chip's rate, where pipelined-dispatch
        rooflines measure the link.  Two-point timing (t_big - t_small
        over the SAME compiled program at two trip counts) also cancels
        the one remaining dispatch+readback.

        ``iters_big`` auto-calibrates so the measured span covers at
        least ``target_seconds`` of device time: for small models the
        default 40-iteration loop is milliseconds, and the dispatch
        penalty's run-to-run variance (tens of ms on this harness) can
        then dominate — or even produce a negative span (measured: the
        QUICK tiny-model int8 ratio read 0.02x from exactly this).

        Inputs are generated on device (distinct per resident batch so
        no content-dedup anywhere can flatter the number; nothing is
        uploaded).  The loop body is the serving ``apply_fn`` — same
        normalise/quantize/softmax path requests take.  The summed-logit
        carry makes every iteration's forward data-dependent-live; XLA
        cannot elide it.
        """
        import jax
        import jax.numpy as jnp

        if not self._loaded:
            self.load()
        batch = int(batch or self.max_batch_size)
        apply_fn = self._apply_fn

        def gen(key):
            return jax.random.randint(
                key, (n_resident, batch, *self.input_shape), 0, 256, dtype=jnp.uint8
            )

        data = jax.jit(gen)(jax.random.key(seed))
        data.block_until_ready()

        def run(variables, data, n):
            def body(i, acc):
                x = jax.lax.dynamic_index_in_dim(
                    data, jnp.mod(i, n_resident), axis=0, keepdims=False
                )
                y = apply_fn(variables, x)
                return acc + jnp.sum(y.astype(jnp.float32))

            return jax.lax.fori_loop(0, n, body, jnp.zeros((), jnp.float32))

        run_jit = jax.jit(run)
        # completion barrier = fetch the scalar: on this harness's
        # backend block_until_ready can return before execution
        # finishes (docs/architecture.md "dispatch modes"); the fetch
        # RTT is constant and cancels in the two-point subtraction
        float(run_jit(self.variables, data, iters_small))  # compile
        t0 = time.perf_counter()
        float(run_jit(self.variables, data, iters_small))
        dt_small = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(run_jit(self.variables, data, iters_big))
        dt_big = time.perf_counter() - t0
        # auto-calibrate: grow iters_big until the measured span covers
        # target_seconds of pure loop time (pilot slope estimates the
        # per-iteration cost without the dispatch constant)
        slope = (dt_big - dt_small) / max(iters_big - iters_small, 1)
        if slope <= 0:
            # dispatch noise swallowed the pilot span (tiny models:
            # dt_big < dt_small by tens of ms happens).  Re-measure the
            # pilots rather than skip calibration — skipping fell
            # through to the dispatch-INCLUSIVE raw rate, exactly the
            # distortion calibration exists to remove.
            for _ in range(3):
                t0 = time.perf_counter()
                float(run_jit(self.variables, data, iters_small))
                dt_small = time.perf_counter() - t0
                t0 = time.perf_counter()
                float(run_jit(self.variables, data, iters_big))
                dt_big = time.perf_counter() - t0
                slope = (dt_big - dt_small) / max(iters_big - iters_small, 1)
                if slope > 0:
                    break
        if slope <= 0:
            # still noise-drowned: the per-iteration cost is far below
            # the dispatch constant, so run the longest loop allowed and
            # measure THAT span — the constant becomes marginal at
            # max_iters scale
            iters_big = max_iters
            t0 = time.perf_counter()
            float(run_jit(self.variables, data, iters_big))
            dt_big = time.perf_counter() - t0
        elif slope * (iters_big - iters_small) < target_seconds:
            iters_big = min(
                max_iters,
                iters_small + max(int(target_seconds / slope), iters_big),
            )
            t0 = time.perf_counter()
            float(run_jit(self.variables, data, iters_big))
            dt_big = time.perf_counter() - t0
        compute = dt_big - dt_small
        if compute <= 1e-4:  # degenerate timing (clock noise): raw rate
            compute = dt_big
            iters_small = 0
        rate = (iters_big - iters_small) * batch / compute
        return {
            "images_per_s": round(rate, 1),
            "batch": batch,
            "iters": iters_big,
            "device_s_per_batch": round(compute / (iters_big - iters_small), 6),
        }

    def class_names(self):
        if self.top_k:  # rows are (indices, scores), not per-class columns
            return []
        if self._class_names:
            return self._class_names
        return [f"t:{i}" for i in range(self.num_classes)]

    def metrics(self):
        if self.batcher is None:
            return []
        return [
            gauge_metric("jaxserver_mean_batch_rows", self.batcher.stats.mean_batch_rows),
            gauge_metric("jaxserver_batches_total", float(self.batcher.stats.batches)),
        ]

    def health_status(self):
        return {
            "model": self.model_name,
            "loaded": self._loaded,
            "precision": self.precision or "bf16",
            "quantize": self.quantize,
            "load_time_s": self._load_time_s,
            "buckets": list(self.batcher.buckets) if self.batcher else [],
            "signatures": [list(s) for s in self.accepted_shapes()] if self._loaded else [],
        }


def jax_server_factory(**kwargs: Any) -> JaxServer:
    return JaxServer(**kwargs)
