"""MLFlowServer — serve MLflow model directories.

Parity component for the reference's mlflowserver
(reference: servers/mlflowserver/mlflowserver/MLFlowServer.py):
download an MLflow model directory from ``model_uri`` and serve its
pyfunc predict.

Two lanes, so the component RUNS even where the mlflow package is
absent (this image — VERDICT r4 missing #4):

* **mlflow lane** — ``mlflow.pyfunc.load_model`` when the package
  imports, exactly the reference's path;
* **fallback lane** — parse the ``MLmodel`` YAML ourselves and serve
  the flavors whose runtimes ARE in this image: ``sklearn`` (the
  reference's canonical mlflowserver demo is an sklearn elasticnet —
  servers/mlflowserver/; joblib/pickle formats both load via joblib)
  and ``python_function`` with ``loader_module: mlflow.sklearn``.
  Other flavors raise with a clear message.

The same class registers as MLFLOW_SERVER either way.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

try:  # the real package wins when present
    import mlflow.pyfunc as _pyfunc
except ImportError:  # fallback lane parses MLmodel directly
    _pyfunc = None

from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent


class _MiniPyfunc:
    """Load an MLmodel directory's sklearn flavor without mlflow."""

    def __init__(self, path: str):
        try:
            # not declared dependencies of this package: a clean install
            # without them must fail with an actionable serving error,
            # not a raw ImportError from the data plane
            import yaml
        except ImportError as e:
            raise MicroserviceError(
                "the mlflow fallback lane needs pyyaml to parse the MLmodel "
                "file — pip install pyyaml (or install mlflow itself for "
                f"the full lane): {e}",
                status_code=500,
                reason="MISSING_DEPENDENCY",
            ) from None

        mlmodel = os.path.join(path, "MLmodel")
        if not os.path.exists(mlmodel):
            raise MicroserviceError(
                f"{path} is not an MLflow model directory (no MLmodel file)",
                status_code=400,
                reason="BAD_MODEL_DIR",
            )
        with open(mlmodel) as f:
            spec = yaml.safe_load(f) or {}
        flavors = spec.get("flavors") or {}
        rel = None
        if "sklearn" in flavors:
            rel = flavors["sklearn"].get("pickled_model", "model.pkl")
        elif flavors.get("python_function", {}).get("loader_module") == "mlflow.sklearn":
            rel = flavors["python_function"].get("model_path", "model.pkl")
        if rel is None:
            raise MicroserviceError(
                "without the mlflow package only the sklearn flavor is "
                f"servable; MLmodel declares {sorted(flavors)}",
                status_code=400,
                reason="NEEDS_MLFLOW",
            )
        try:
            import joblib
        except ImportError as e:
            raise MicroserviceError(
                "the mlflow fallback lane needs joblib to load the sklearn "
                "flavor — pip install joblib (or install mlflow itself for "
                f"the full lane): {e}",
                status_code=500,
                reason="MISSING_DEPENDENCY",
            ) from None

        self.model = joblib.load(os.path.join(path, rel))

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.predict(np.asarray(X)))


class MLFlowServer(TPUComponent):
    def __init__(self, model_uri: str = "", **kwargs: Any):
        super().__init__(**kwargs)
        self.model_uri = model_uri
        self.model: Optional[Any] = None

    def load(self) -> None:
        if self.model is not None:
            return
        if not self.model_uri:
            raise MicroserviceError(
                "MLFlowServer needs a model_uri", status_code=400,
                reason="MISSING_MODEL_URI",
            )
        from seldon_core_tpu.utils import storage

        path = storage.download(self.model_uri)
        if _pyfunc is not None:
            self.model = _pyfunc.load_model(path)
        else:
            self.model = _MiniPyfunc(path)

    def predict(self, X, names, meta=None):
        if self.model is None:
            self.load()
        return np.asarray(self.model.predict(np.asarray(X)))
