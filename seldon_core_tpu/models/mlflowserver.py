"""MLFlowServer — serve MLflow pyfunc models (gated on mlflow).

Parity component for the reference's mlflowserver
(reference: servers/mlflowserver/mlflowserver/MLFlowServer.py):
download an MLflow model directory from ``model_uri`` and serve its
pyfunc predict.  Registered as MLFLOW_SERVER when mlflow is importable.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

import mlflow.pyfunc  # noqa: F401 — gate: ImportError skips registration

from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent


class MLFlowServer(TPUComponent):
    def __init__(self, model_uri: str = "", **kwargs: Any):
        super().__init__(**kwargs)
        self.model_uri = model_uri
        self.model = None

    def load(self) -> None:
        if self.model is not None:
            return
        if not self.model_uri:
            raise MicroserviceError("MLFlowServer needs a model_uri", status_code=400, reason="MISSING_MODEL_URI")
        from seldon_core_tpu.utils import storage

        path = storage.download(self.model_uri)
        self.model = mlflow.pyfunc.load_model(path)

    def predict(self, X, names, meta=None):
        if self.model is None:
            self.load()
        return np.asarray(self.model.predict(np.asarray(X)))
