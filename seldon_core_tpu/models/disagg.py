"""Disaggregated prefill/decode serving: dedicated prefill workers
streaming finished KV pages into a decode engine's pool (r15).

Chunked prefill (``SELDON_TPU_CHUNK_TOKEN_BUDGET``) removes most of the
prefill/decode interference inside ONE engine; DistServe
(arXiv:2401.09670) shows the rest goes away by not sharing the engine
at all: prompt prefill runs on dedicated PREFILL workers and only the
finished KV pages enter the DECODE worker's pool, so decode waves never
carry prefill FLOPs and interactive TTFT stops competing with batch
prompts for the decode engine's cadence.

Two handoff lanes, one wire format (the SRT1 container of
``codec/bufview.pack_kv_handoff``):

* **local (in-process workers)** — the payload's page buffers pass BY
  REFERENCE (metered as ``zero_copy_bytes``); the decode engine's page
  scatter is the single copy the hardware requires — re-encoding
  through the wire container in-process would be a full host memcpy
  per request.  This is the ICI-attached topology: prefill and decode
  engines in one process, different chips.
* **DCN (remote workers)** — :class:`PrefillLM` is an ordinary
  deployable microservice returning the same container as a uint8
  rawTensor proto; :class:`DisaggregatedLM` dials it through the
  standard transport clients (breakers, retries, tracing and deadline
  re-injection apply unchanged).

Admission prices a request by its PREDICTED prefill+decode cost
(``PagedEngine.predict_cost_s`` — measured rates, no tuning): a
deadline the prediction cannot meet is rejected with 504
``DEADLINE_UNREACHABLE`` before a prefill worker burns a single FLOP on
it.  The r10 priority/preemption machinery is untouched — priorities
and deadlines ride the handoff into the decode engine's ordinary
``submit`` path.
"""

from __future__ import annotations

import logging
import queue as _pyqueue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.models.paged import PagedEngine, StreamingLM
from seldon_core_tpu.runtime import knobs as _knobs
from seldon_core_tpu.runtime.component import MicroserviceError

logger = logging.getLogger(__name__)

__all__ = ["PrefillLM", "DisaggregatedLM", "evacuate_streams",
           "migration_journal_entry"]


# ---------------------------------------------------------------------------
# live-stream evacuation coordinator (r17)
# ---------------------------------------------------------------------------


def migration_journal_entry(payload: Dict[str, Any]) -> Dict[str, Any]:
    """A drain-journal entry from a migration payload — the fallback
    recipe when a stream's export succeeded but no peer would take it:
    the respawned (or surviving) engine re-derives the stream exactly
    as an r12 journal replay would.  Schema comes from the ONE shared
    builder (``models/paged.journal_entry``), so the two journal lanes
    cannot drift."""
    from seldon_core_tpu.models.paged import journal_entry

    return journal_entry(
        req_id=payload.get("req_id"),
        prompt=[int(t) for t in np.asarray(payload["prompt"]).reshape(-1)],
        max_new_tokens=int(payload.get("max_new_tokens", 32)),
        temperature=float(payload.get("temperature", 0.0)),
        top_k=int(payload.get("top_k", 0)),
        eos_id=int(payload.get("eos_id", -1)),
        seed=int(payload.get("seed", 0)),
        priority=int(payload.get("priority", 0)),
        deadline_remaining_ms=payload.get("deadline_remaining_ms"),
        streamed=int(payload.get("streamed") or 0),
        stream_tokens=bool(payload.get("stream_tokens")),
        tokens_decoded=int(
            np.asarray(payload.get("tokens", [])).reshape(-1).shape[0]
        ),
        adapter=payload.get("adapter"),
    )


def _peer_cost_s(engine: PagedEngine, payload: Dict[str, Any]) -> float:
    """Predicted seconds for ``payload``'s REMAINING work on ``engine``
    (the PR 13 cost model applied to evacuation placement).  A cold
    peer prices 0.0 — an idle engine is the best destination anyway;
    queue depth breaks ties so one peer doesn't absorb the whole
    evacuation wave."""
    remaining = max(
        1,
        int(payload.get("max_new_tokens", 32))
        - int(np.asarray(payload.get("tokens", [])).reshape(-1).shape[0]),
    )
    cost = engine.predict_cost_s(0, remaining)  # KV arrives computed:
    # the peer pays decode only, never the prompt's prefill FLOPs
    stats = engine.engine_stats()
    backlog = stats["queued_streams"] + stats["active_slots"]
    return (cost or 0.0) + 0.001 * backlog


def evacuate_streams(
    src_engine: PagedEngine,
    peers: List[PagedEngine],
    *,
    transport: str = "local",
) -> Dict[str, Any]:
    """Live-migrate ``src_engine``'s exportable streams onto healthy
    ``peers`` (r17): priority-ordered (highest first — the evacuation
    window's budget goes to the most important streams), each placed on
    the HEALTHY peer whose predicted remaining-work cost is lowest (the
    PR 13 cost model; degraded/evacuating peers are never targets).
    The in-process lane adopts the source's stream objects, so waiter
    events and token queues survive the move — zero token loss.

    A stream every peer refuses (pool too small, engine closed, shed)
    falls back to the r12 discipline: its waiter resolves 503
    ``MIGRATING`` and its re-derivation recipe lands in the returned
    ``journal`` list for the caller to persist.  Returns
    ``{"migrated", "failed", "journal"}``."""
    from seldon_core_tpu.engine.transport import migration_hop

    exported = src_engine.migrate_export()
    healthy = [
        p for p in peers
        if p is not src_engine
        and p.engine_stats().get("health", "healthy") == "healthy"
    ]
    out: Dict[str, Any] = {"migrated": 0, "failed": 0, "journal": []}
    err = MicroserviceError(
        "stream could not be live-migrated during evacuation; its "
        "recipe is journaled for re-derivation",
        status_code=503, reason="MIGRATING",
    )
    for payload, stream in sorted(
        exported, key=lambda ps: -ps[0]["priority"]
    ):
        placed = False
        for peer in sorted(healthy, key=lambda p: _peer_cost_s(p, payload)):
            try:
                with migration_hop("evacuate", transport) as hop:
                    if hop is not None:
                        hop.zero_copy_bytes = (
                            int(np.asarray(payload["k"]).nbytes)
                            + int(np.asarray(payload["v"]).nbytes)
                        )
                    peer.migrate_import(payload, stream=stream)
                placed = True
                break
            except MicroserviceError as exc:
                logger.warning(
                    "peer refused migrated req %s: %s",
                    payload.get("req_id"), exc,
                )
        if placed:
            out["migrated"] += 1
        else:
            out["failed"] += 1
            out["journal"].append(migration_journal_entry(payload))
            src_engine.fail_stream(stream, err)
    return out


class PrefillLM(StreamingLM):
    """Deployable PREFILL-WORKER role: admits one prompt per request,
    runs its (chunked, when the budget knob is on) prefill, and returns
    the KV-page handoff container as a uint8 row — which the runtime
    encodes as a rawTensor proto, the DCN wire form of the handoff.
    Decode never runs here: every stream is ``kv_export``, so the
    engine's waves are pure prefill and its prefix cache stays warm
    across exports (a shared system prompt is computed once per
    worker)."""

    def predict(self, X, names, meta=None):
        if self.engine is None:
            self.load()  # idempotent + internally locked
        meta = meta or {}
        tags = meta.get("tags", {})
        X = np.atleast_2d(np.asarray(X, np.int32))
        if X.shape[0] != 1:
            raise MicroserviceError(
                "prefill workers serve one prompt per request (the KV "
                "handoff is per stream); send rows separately",
                status_code=400, reason="BAD_REQUEST",
            )
        priority, deadline = self._slo_terms(tags)
        stream = self.engine.submit(
            X[0], max_new_tokens=1, priority=priority, deadline=deadline,
            kv_export=True, adapter=self._request_adapter(tags),
        )
        self._wake.set()
        stream.event.wait()
        if stream.error is not None:
            raise stream.error
        from seldon_core_tpu.codec.bufview import pack_kv_handoff

        buf = pack_kv_handoff(stream.kv_payload)
        return np.frombuffer(buf, np.uint8)[None, :]

    def metrics(self):
        out = super().metrics()
        if self.engine is not None:
            s = self.engine.engine_stats()
            out.append({
                "type": "GAUGE", "key": "paged_kv_exports",
                "value": s["kv_exports"],
            })
        return out


class _PrefillJob:
    """One prompt waiting for a prefill worker.  Orders by (priority
    desc, arrival) in the shared PriorityQueue — the same
    highest-class-first discipline the decode engine's admission uses,
    so a batch prompt cannot starve interactive prefills either."""

    __slots__ = ("seq", "prompt", "priority", "submit_kw", "event",
                 "stream", "error", "cancelled")

    def __init__(self, seq: int, prompt: np.ndarray, priority: int,
                 submit_kw: Dict[str, Any]):
        self.seq = seq
        self.prompt = prompt
        self.priority = priority
        self.submit_kw = submit_kw
        self.event = threading.Event()
        self.stream = None
        self.error: Optional[Exception] = None
        # set by the coordinator's error cleanup: a job still queued
        # when a sibling fails must not burn prefill FLOPs and decode
        # capacity on a result nobody will read
        self.cancelled = False

    def __lt__(self, other: "_PrefillJob") -> bool:
        return (-self.priority, self.seq) < (-other.priority, other.seq)


class DisaggregatedLM(StreamingLM):
    """Decode-worker front with dedicated prefill workers.

    ``prefill_workers=N`` (or ``SELDON_TPU_PREFILL_WORKERS``) builds N
    in-process prefill engines (``prefill_slots`` admission slots each)
    fed from one priority job queue; ``prefill_endpoints=[...]``
    instead dials remote :class:`PrefillLM` microservices (``"host:
    port"`` or ``"grpc://"``/``"rest://"`` URLs) — the supervisor's
    ``disagg_worker_specs`` wires exactly that topology up.  With
    neither configured this degrades to a plain :class:`StreamingLM`.

    ``predict``/``predict_stream`` route every prompt through a prefill
    worker and admit only the finished KV pages into the decode engine,
    so the decode loop's waves carry decode (and KV scatters) only.
    Greedy decode is bit-exact with unified serving: the imported pages
    are the same deterministic prefill KV, and the decode stream's rng
    keys derive from the same per-request seed rule."""

    def __init__(
        self,
        *args: Any,
        prefill_workers: int = 0,
        prefill_slots: int = 2,
        prefill_endpoints: Any = None,
        admission_pricing: Optional[bool] = None,
        **kwargs: Any,
    ):
        super().__init__(*args, **kwargs)
        if not prefill_workers:
            prefill_workers = int(
                _knobs.raw("SELDON_TPU_PREFILL_WORKERS", "0") or 0
            )
        self.prefill_workers = max(0, int(prefill_workers))
        self.prefill_slots = max(1, int(prefill_slots))
        if isinstance(prefill_endpoints, str):
            # deployment parameters arrive as a JSON string
            import json as _json

            prefill_endpoints = (
                _json.loads(prefill_endpoints) if prefill_endpoints else []
            )
        self.prefill_endpoints = list(prefill_endpoints or [])
        if admission_pricing is None:
            admission_pricing = _knobs.flag("SELDON_TPU_ADMISSION_PRICING")
        self.admission_pricing = bool(admission_pricing)
        self._prefill_engines: List[PagedEngine] = []
        self._prefill_threads: List[threading.Thread] = []
        self._jobs: "_pyqueue.PriorityQueue[_PrefillJob]" = (
            _pyqueue.PriorityQueue()
        )
        self._workers_stop = False
        self._job_seq = 0
        self._disagg_lock = threading.Lock()

    # ---- lifecycle --------------------------------------------------------

    def _disagg_enabled(self) -> bool:
        return bool(self.prefill_workers or self.prefill_endpoints)

    def load(self) -> None:
        super().load()
        role = _knobs.raw("SELDON_TPU_DISAGG_ROLE", "") or ""
        if role:
            # supervisor-pinned role: surfaced in logs and /debug/knobs
            # so operators can tell a worker's role without guessing
            # from its traffic
            logger.info("disaggregated role pin: %s", role)
        if not self._disagg_enabled():
            return
        with self._disagg_lock:
            if self._prefill_threads:
                return
            if self.prefill_endpoints:
                for i, ep in enumerate(self.prefill_endpoints):
                    t = threading.Thread(
                        target=self._remote_prefill_loop, args=(ep,),
                        name=f"disagg-prefill-dcn-{i}", daemon=True,
                    )
                    t.start()
                    self._prefill_threads.append(t)
                return
            import jax.numpy as jnp

            from seldon_core_tpu.models.generate import load_lm_params

            # same URI/config/seed as the decode engine -> identical
            # params, which is the bit-exactness precondition of the
            # handoff (documented in docs §5b-quater)
            params = load_lm_params(self.model_uri, self.config, self.seed)
            eng_cfg = dict(self.engine_config)
            eng_cfg.update(
                max_slots=self.prefill_slots,
                # prefill-only engines never decode: speculative verify
                # and queue bounds belong to the decode worker
                speculative=None, max_queue=0,
            )
            # adapter-carrying prompts prefill WITH their adapter (the
            # exported KV must match the decode worker's weight set) —
            # prefill engines resolve the same registry names
            registry = self._register_adapters()
            for i in range(self.prefill_workers):
                eng = PagedEngine(
                    params, dtype=jnp.bfloat16, tp=self.tp or None,
                    max_adapters=self.max_adapters,
                    lora_rank=self.lora_rank, weight_registry=registry,
                    **self.config, **eng_cfg,
                )
                self._prefill_engines.append(eng)
                t = threading.Thread(
                    target=self._prefill_loop, args=(eng,),
                    name=f"disagg-prefill-{i}", daemon=True,
                )
                t.start()
                self._prefill_threads.append(t)

    def shutdown(self) -> None:
        self._workers_stop = True
        super().shutdown()
        for eng in self._prefill_engines:
            try:
                eng.close()
            except Exception:  # noqa: BLE001 — teardown must finish even if
                # a worker engine already failed
                logger.exception("prefill engine close failed")

    # ---- priced admission -------------------------------------------------

    def _price_admission(
        self, prompt_len: int, max_new: int, deadline: Optional[float]
    ) -> None:
        """DistServe-style priced admission: a request whose PREDICTED
        prefill+decode cost cannot fit its remaining deadline is
        rejected BEFORE a prefill worker burns FLOPs on it — dead-on-
        arrival work is the overload amplifier the r10 shedding policy
        cannot see (it prices queue position, not service time)."""
        if (
            not self.admission_pricing
            or deadline is None
            or self.engine is None
        ):
            return
        cost = self.engine.predict_cost_s(int(prompt_len), int(max_new))
        if cost is None:
            return  # cold engine: nothing measured yet, admit unpriced
        remaining = deadline - time.monotonic()
        if cost > remaining:
            raise MicroserviceError(
                f"admission priced out: predicted prefill+decode cost "
                f"{cost * 1000.0:.0f} ms exceeds the remaining deadline "
                f"{max(0.0, remaining) * 1000.0:.0f} ms",
                status_code=504, reason="DEADLINE_UNREACHABLE",
            )

    # ---- prefill workers --------------------------------------------------

    def _enqueue_prefill(
        self, prompt: np.ndarray, priority: int, submit_kw: Dict[str, Any]
    ) -> _PrefillJob:
        with self._disagg_lock:
            self._job_seq += 1
            job = _PrefillJob(self._job_seq, prompt, priority, submit_kw)
        self._jobs.put(job)
        return job

    def _hand_off_local(self, job: _PrefillJob, payload: Dict[str, Any]) -> None:
        """In-process handoff: the payload's page buffers pass BY
        REFERENCE into the decode engine (its donated scatter is the
        single copy the hardware requires — re-encoding through the
        wire container here would be a full host memcpy per request),
        metered through the transport surface (``method="kv_handoff"``,
        ``zero_copy_bytes``) so dashboards price the lane next to the
        request lanes it displaces."""
        from seldon_core_tpu.engine.transport import kv_handoff_hop

        with kv_handoff_hop("disagg-prefill", "local") as hop:
            if hop is not None:
                hop.zero_copy_bytes = sum(
                    int(np.asarray(payload[k]).nbytes)
                    for k in ("k", "v", "last_logits", "prompt",
                              "k_scales", "v_scales")
                    if k in payload
                )
            job.stream = self.engine.submit_prefilled(
                payload, **job.submit_kw
            )
        self._wake.set()

    def _hand_off_container(self, job: _PrefillJob, buf: bytes) -> None:
        """DCN handoff: reopen the received SRT1 container as zero-copy
        views and admit the pages, metering the transferred bytes.  The
        ``transport.corrupt`` chaos point flips payload bytes HERE —
        the CRC32C trailer must turn the flip into a named rejection
        the waiter sees, never a silent garbage-KV scatter."""
        from seldon_core_tpu.codec.bufview import unpack_kv_handoff
        from seldon_core_tpu.engine.transport import kv_handoff_hop
        from seldon_core_tpu.utils import faults as _faults

        buf = _faults.corrupt_bytes("transport.corrupt", buf)
        with kv_handoff_hop("disagg-prefill", "dcn") as hop:
            if hop is not None:
                hop.request_bytes = len(buf)
            payload = unpack_kv_handoff(buf)
            job.stream = self.engine.submit_prefilled(
                payload, **job.submit_kw
            )
        self._wake.set()

    def _prefill_loop(self, eng: PagedEngine) -> None:
        """In-process worker: pop a job, prefill-export on this
        worker's own engine (it owns the step loop — the single-stepper
        invariant holds per engine), hand the pages off by reference."""
        while not self._workers_stop:
            try:
                job = self._jobs.get(timeout=0.2)
            except _pyqueue.Empty:
                continue
            if job.cancelled:
                job.event.set()
                continue
            try:
                payload = eng.prefill_export(
                    job.prompt,
                    priority=job.priority,
                    deadline=job.submit_kw.get("deadline"),
                    adapter=job.submit_kw.get("adapter"),
                )
                if job.cancelled:  # cancelled mid-export: don't admit
                    continue
                self._hand_off_local(job, payload)
            except Exception as exc:  # noqa: BLE001 — the waiter gets the
                # error; the worker thread must survive any one job
                job.error = exc
            finally:
                job.event.set()

    def _remote_prefill_loop(self, endpoint: str) -> None:
        """DCN worker: pop a job, call the remote :class:`PrefillLM`'s
        predict through the standard transport clients' model-call
        method (``transform_input`` — the executor's MODEL predict
        verb; breakers/retries/deadline re-injection apply), hand the
        returned container off.  One thread per endpoint, with ONE
        persistent event loop for its lifetime: ``GrpcClient`` caches
        ``grpc.aio`` channels per address, and a channel outliving a
        per-call ``asyncio.run`` loop would fail every RPC after the
        first ("event loop is closed")."""
        import asyncio

        from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
        from seldon_core_tpu.engine.transport import GrpcClient, RestClient
        from seldon_core_tpu.runtime.message import InternalMessage

        scheme, sep, rest = endpoint.partition("://")
        if not sep:
            scheme, rest = "grpc", endpoint
        host, _, port = rest.partition(":")
        spec = UnitSpec(
            name=f"prefill@{rest}",
            endpoint=Endpoint(
                host=host or "localhost", port=int(port or 9000),
                transport="REST" if scheme == "rest" else "GRPC",
            ),
        )
        client = (
            RestClient(spec) if scheme == "rest" else GrpcClient(spec)
        )
        loop = asyncio.new_event_loop()
        try:
            while not self._workers_stop:
                try:
                    job = self._jobs.get(timeout=0.2)
                except _pyqueue.Empty:
                    continue
                if job.cancelled:
                    job.event.set()
                    continue
                try:
                    msg = InternalMessage(payload=np.atleast_2d(job.prompt))
                    msg.meta.tags["priority"] = job.priority
                    # the remote PrefillLM must prefill with the SAME
                    # weight set the decode engine will decode with
                    if job.submit_kw.get("adapter"):
                        msg.meta.tags["adapter"] = job.submit_kw["adapter"]
                    # the deadline must CROSS the DCN hop: the remote
                    # PrefillLM mints its own expiry from the remaining
                    # budget (its _slo_terms reads deadline_ms), and a
                    # job already expired while queued here fast-fails
                    # before burning a remote prefill on it
                    deadline = job.submit_kw.get("deadline")
                    if deadline is not None:
                        remaining_ms = (deadline - time.monotonic()) * 1000.0
                        if remaining_ms <= 0:
                            from seldon_core_tpu.utils.deadlines import (
                                deadline_exceeded,
                            )

                            raise deadline_exceeded(
                                "disaggregated prefill queue"
                            )
                        msg.meta.tags["deadline_ms"] = remaining_ms
                    reply = loop.run_until_complete(
                        client.transform_input(msg)
                    )
                    buf = np.ascontiguousarray(
                        reply.array(), dtype=np.uint8
                    ).tobytes()
                    if job.cancelled:  # cancelled mid-call: don't admit
                        continue
                    self._hand_off_container(job, buf)
                except Exception as exc:  # noqa: BLE001 — the waiter gets
                    # the error; the worker thread must survive any one job
                    job.error = exc
                finally:
                    job.event.set()
        finally:
            loop.close()

    # ---- serving fronts ---------------------------------------------------

    def predict(self, X, names, meta=None):
        if self.engine is None:
            self.load()  # idempotent + internally locked
        if not self._disagg_enabled():
            return super().predict(X, names, meta)
        meta = meta or {}
        tags = meta.get("tags", {})
        max_new = int(tags.get("max_new_tokens", self.max_new_tokens))
        temperature = float(tags.get("temperature", self.temperature))
        top_k = int(tags.get("top_k", self.top_k))
        request_seed = self._request_seed(tags, meta)
        priority, deadline = self._slo_terms(tags)
        adapter = self._request_adapter(tags)
        X = np.atleast_2d(np.asarray(X, np.int32))
        jobs: List[_PrefillJob] = []
        try:
            for i, row in enumerate(X):
                self._price_admission(len(row), max_new, deadline)
                jobs.append(self._enqueue_prefill(
                    row, priority,
                    dict(
                        max_new_tokens=max_new, temperature=temperature,
                        top_k=top_k, eos_id=self.eos_id,
                        seed=self.seed ^ (request_seed * 1000003 + i),
                        priority=priority, deadline=deadline,
                        adapter=adapter,
                    ),
                ))
            out = []
            for job in jobs:
                job.event.wait()
                if job.error is not None:
                    raise job.error
                job.stream.event.wait()
                if job.stream.error:
                    raise job.stream.error
                out.append(job.stream.result)
            return np.stack(out)
        except BaseException:
            # one row priced out/shed/errored: the siblings must not
            # keep burning prefill FLOPs or decoding unread (same
            # discipline as StreamingLM) — jobs still queued are
            # flagged so the workers skip them, jobs already handed
            # off cancel their decode streams
            for job in jobs:
                job.cancelled = True
                s = job.stream
                if s is not None and s.result is None and s.error is None:
                    self.engine.cancel(s)
            raise

    def predict_stream(self, X, names=None, meta=None):
        if self.engine is None:
            self.load()  # idempotent + internally locked
        if not self._disagg_enabled():
            yield from super().predict_stream(X, names, meta)
            return
        meta = meta or {}
        tags = meta.get("tags", {})
        max_new = int(tags.get("max_new_tokens", self.max_new_tokens))
        temperature = float(tags.get("temperature", self.temperature))
        top_k = int(tags.get("top_k", self.top_k))
        request_seed = self._request_seed(tags, meta)
        priority, deadline = self._slo_terms(tags)
        X = np.atleast_2d(np.asarray(X, np.int32))
        if X.shape[0] != 1:
            raise MicroserviceError(
                "token streaming serves one prompt per stream; send rows "
                "separately (predict() batches them)",
                status_code=400, reason="BAD_REQUEST",
            )
        self._price_admission(X.shape[1], max_new, deadline)
        job = self._enqueue_prefill(
            X[0], priority,
            dict(
                max_new_tokens=max_new, temperature=temperature,
                top_k=top_k, eos_id=self.eos_id,
                seed=self.seed ^ (request_seed * 1000003),
                priority=priority, deadline=deadline,
                stream_tokens=True,
                adapter=self._request_adapter(tags),
            ),
        )
        job.event.wait()
        if job.error is not None:
            raise job.error
        stream = job.stream
        try:
            while True:
                got = stream.token_queue.get()
                if got is None:
                    break
                yield np.asarray(got, np.int32)
            if stream.error:
                raise stream.error
        finally:
            self.engine.cancel(stream)

    def metrics(self):
        out = super().metrics()
        if self.engine is not None:
            s = self.engine.engine_stats()
            out.append({
                "type": "GAUGE", "key": "paged_kv_imports",
                "value": s["kv_imports"],
            })
        exports = 0
        for eng in self._prefill_engines:
            exports += eng.engine_stats()["kv_exports"]
        if self._disagg_enabled():
            out.append({
                "type": "GAUGE", "key": "paged_prefill_workers",
                "value": (
                    len(self._prefill_engines) or len(self.prefill_endpoints)
                ),
            })
            out.append({
                "type": "GAUGE", "key": "paged_kv_exports", "value": exports,
            })
        return out
