"""Generated protobuf bindings for the seldon-core-tpu wire contract.

`seldon_pb2` is generated from `seldon.proto` (see the repo Makefile's
`proto` target).  The contract is wire-compatible with the reference's
`proto/prediction.proto:14-128`.
"""

from seldon_core_tpu.proto import seldon_pb2 as pb  # noqa: F401

SeldonMessage = pb.SeldonMessage
SeldonMessageList = pb.SeldonMessageList
DefaultData = pb.DefaultData
Tensor = pb.Tensor
RawTensor = pb.RawTensor
Meta = pb.Meta
Metric = pb.Metric
Status = pb.Status
Feedback = pb.Feedback
RequestResponse = pb.RequestResponse
