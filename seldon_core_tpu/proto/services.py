"""gRPC service descriptions for the seven node-role services.

The environment has no ``grpcio-tools`` code generator, so instead of
generated stub classes we describe each service as a method table and
build servers/clients with gRPC's generic-handler API.  The services and
method signatures mirror the reference contract
(reference: proto/prediction.proto:94-128).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from seldon_core_tpu.proto import pb

PACKAGE = "seldon.protos"

# service name -> {method name -> (request class, response class)}
SERVICES: Dict[str, Dict[str, Tuple[type, type]]] = {
    "Generic": {
        "TransformInput": (pb.SeldonMessage, pb.SeldonMessage),
        "TransformOutput": (pb.SeldonMessage, pb.SeldonMessage),
        "Route": (pb.SeldonMessage, pb.SeldonMessage),
        "Aggregate": (pb.SeldonMessageList, pb.SeldonMessage),
        "SendFeedback": (pb.Feedback, pb.SeldonMessage),
    },
    "Model": {
        "Predict": (pb.SeldonMessage, pb.SeldonMessage),
        "SendFeedback": (pb.Feedback, pb.SeldonMessage),
    },
    "Router": {
        "Route": (pb.SeldonMessage, pb.SeldonMessage),
        "SendFeedback": (pb.Feedback, pb.SeldonMessage),
    },
    "Transformer": {
        "TransformInput": (pb.SeldonMessage, pb.SeldonMessage),
    },
    "OutputTransformer": {
        "TransformOutput": (pb.SeldonMessage, pb.SeldonMessage),
    },
    "Combiner": {
        "Aggregate": (pb.SeldonMessageList, pb.SeldonMessage),
    },
    "Seldon": {
        "Predict": (pb.SeldonMessage, pb.SeldonMessage),
        "SendFeedback": (pb.Feedback, pb.SeldonMessage),
    },
}


# stream-stream methods (transport-level chunking; additive to the
# reference contract): method -> (chunk class in, chunk class out)
STREAM_METHODS: Dict[str, Dict[str, Tuple[type, type]]] = {
    "Seldon": {
        "PredictStream": (pb.MessageChunk, pb.MessageChunk),
    },
}

# unary-stream methods (server streaming; additive): token streaming
# for the generation family — one prompt in, incremental token chunks
# out as the continuous-batching engine emits them
UNARY_STREAM_METHODS: Dict[str, Dict[str, Tuple[type, type]]] = {
    "Seldon": {
        "GenerateStream": (pb.SeldonMessage, pb.SeldonMessage),
    },
}

# default chunk payload size for the streaming lanes (1 MiB keeps each
# frame comfortably under any configured gRPC message cap)
STREAM_CHUNK_BYTES = 1 << 20

# total reassembled-message cap for a stream (env-overridable): the
# per-frame gRPC limit stops bounding memory once frames accumulate,
# so the stream lane enforces its own ceiling
import os as _os

STREAM_MAX_BYTES = int(_os.environ.get("SELDON_STREAM_MAX_BYTES", str(2 << 30)))


def chunk_message(msg, chunk_bytes: int = STREAM_CHUNK_BYTES):
    """Serialize a proto message into a MessageChunk iterator."""
    raw = msg.SerializeToString()
    if not raw:
        yield pb.MessageChunk(data=b"")
        return
    for off in range(0, len(raw), chunk_bytes):
        yield pb.MessageChunk(data=raw[off:off + chunk_bytes])


def assemble_chunks(chunks, cls):
    """Reassemble a MessageChunk iterable into a `cls` message."""
    return cls.FromString(b"".join(c.data for c in chunks))


def full_service_name(service: str) -> str:
    return f"{PACKAGE}.{service}"


def method_path(service: str, method: str) -> str:
    """The gRPC request path, e.g. ``/seldon.protos.Model/Predict``."""
    return f"/{PACKAGE}.{service}/{method}"


def generic_handler(service: str, dispatch: Dict[str, Callable]):
    """Build a grpc generic handler for `service`.

    `dispatch` maps method name -> callable(request, context) -> response.
    Methods absent from `dispatch` are omitted (gRPC returns UNIMPLEMENTED).
    """
    import grpc

    handlers = {}
    for method, (req_cls, resp_cls) in SERVICES[service].items():
        fn = dispatch.get(method)
        if fn is None:
            continue
        handlers[method] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg, _c=resp_cls: msg.SerializeToString(),
        )
    for method, (req_cls, resp_cls) in STREAM_METHODS.get(service, {}).items():
        fn = dispatch.get(method)
        if fn is None:
            continue
        handlers[method] = grpc.stream_stream_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg, _c=resp_cls: msg.SerializeToString(),
        )
    for method, (req_cls, resp_cls) in UNARY_STREAM_METHODS.get(service, {}).items():
        fn = dispatch.get(method)
        if fn is None:
            continue
        handlers[method] = grpc.unary_stream_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg, _c=resp_cls: msg.SerializeToString(),
        )
    return grpc.method_handlers_generic_handler(full_service_name(service), handlers)


def stream_callable(channel, service: str, method: str):
    """Client-side stream-stream callable for service/method."""
    _req_cls, resp_cls = STREAM_METHODS[service][method]
    return channel.stream_stream(
        method_path(service, method),
        request_serializer=lambda msg: msg.SerializeToString(),
        response_deserializer=resp_cls.FromString,
    )


def unary_stream_callable(channel, service: str, method: str):
    """Client-side unary-stream callable (server streaming)."""
    _req_cls, resp_cls = UNARY_STREAM_METHODS[service][method]
    return channel.unary_stream(
        method_path(service, method),
        request_serializer=lambda msg: msg.SerializeToString(),
        response_deserializer=resp_cls.FromString,
    )


def unary_callable(channel, service: str, method: str):
    """Build a client-side unary-unary callable for service/method."""
    req_cls, resp_cls = SERVICES[service][method]
    return channel.unary_unary(
        method_path(service, method),
        request_serializer=lambda msg: msg.SerializeToString(),
        response_deserializer=resp_cls.FromString,
    )
