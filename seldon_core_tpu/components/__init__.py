"""Reusable graph components: learned routers, outlier detectors.

Importing registers them as declarative builtin implementations
(reference analogue: components/ selected via image names; here via the
implementation registry).
"""

from seldon_core_tpu.engine.units import register_implementation
from seldon_core_tpu.components.routers import EpsilonGreedy, ThompsonSampling  # noqa: F401

register_implementation("EPSILON_GREEDY", EpsilonGreedy)
register_implementation("THOMPSON_SAMPLING", ThompsonSampling)

try:  # detectors that need only numpy/jax register unconditionally
    from seldon_core_tpu.components.outliers import (  # noqa: F401
        IsolationForestDetector,
        MahalanobisDetector,
        Seq2SeqOutlierDetector,
        VAEOutlierDetector,
    )

    register_implementation("OUTLIER_MAHALANOBIS", MahalanobisDetector)
    register_implementation("OUTLIER_VAE", VAEOutlierDetector)
    register_implementation("OUTLIER_ISOLATION_FOREST", IsolationForestDetector)
    register_implementation("OUTLIER_SEQ2SEQ", Seq2SeqOutlierDetector)
except ImportError:  # pragma: no cover
    pass
