"""Online outlier detection components.

Dual-use components like the reference's detectors
(reference: components/outlier-detection/mahalanobis/
CoreMahalanobis.py:7-50): deployable as a MODEL (returns outlier
scores) or as an input TRANSFORMER (passes data through unchanged while
tagging outliers in ``meta.tags`` and counting them in custom metrics).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.runtime.component import TPUComponent, counter_metric, gauge_metric


class VAEOutlierDetector(TPUComponent):
    """Variational-autoencoder outlier detection (reference analogue:
    components/outlier-detection/vae/CoreVAE.py:11-170, a Keras model
    with a train.py — here a flax model trained with a jit-compiled
    step on the same device mesh serving uses).

    Scoring: reconstruction error (MSE) of the encoded/decoded input;
    rows above ``threshold`` flag as outliers.  Train with ``fit`` on
    normal data before deploying, or load trained params via
    ``model_uri`` (flax msgpack).
    """

    def __init__(
        self,
        n_features: int = 0,
        latent_dim: int = 2,
        hidden_dim: int = 32,
        threshold: float = 0.5,
        model_uri: str = "",
        seed: int = 0,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.n_features = int(n_features)
        self.latent_dim = int(latent_dim)
        self.hidden_dim = int(hidden_dim)
        self.threshold = float(threshold)
        self.model_uri = model_uri
        self.seed = int(seed)
        self.module = None
        self.params = None
        self._score_jit = None
        self._last_scores = np.array([])
        self._last_flags = np.array([], dtype=bool)

    def _build(self, n_features: int):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        latent, hidden = self.latent_dim, self.hidden_dim

        class VAE(nn.Module):
            @nn.compact
            def __call__(self, x, rng):
                h = nn.relu(nn.Dense(hidden, name="enc1")(x))
                mu = nn.Dense(latent, name="mu")(h)
                logvar = nn.Dense(latent, name="logvar")(h)
                eps = jax.random.normal(rng, mu.shape)
                z = mu + jnp.exp(0.5 * logvar) * eps
                h2 = nn.relu(nn.Dense(hidden, name="dec1")(z))
                recon = nn.Dense(n_features, name="out")(h2)
                return recon, mu, logvar

        self.n_features = n_features
        self.module = VAE()
        import jax

        self.params = self.module.init(
            jax.random.key(self.seed), jnp.zeros((1, n_features)), jax.random.key(0)
        )

        def score_fn(params, x):
            recon, _, _ = self.module.apply(params, x, jax.random.key(0))
            return jnp.mean((x - recon) ** 2, axis=-1)

        self._score_jit = jax.jit(score_fn)

    def load(self) -> None:
        if self.model_uri:
            import jax

            from flax import serialization

            from seldon_core_tpu.utils import storage

            if self.module is None:
                if not self.n_features:
                    raise ValueError("VAEOutlierDetector needs n_features with model_uri")
                self._build(self.n_features)
            path = storage.download(self.model_uri)
            with open(path, "rb") as f:
                self.params = serialization.from_bytes(self.params, f.read())

    def fit(self, X: np.ndarray, epochs: int = 50, learning_rate: float = 1e-2,
            kl_weight: float = 1e-3, batch_size: int = 128) -> List[float]:
        """Train on normal data; returns per-epoch losses."""
        import jax
        import jax.numpy as jnp
        import optax

        X = np.asarray(X, dtype=np.float32)
        if self.module is None:
            self._build(X.shape[1])
        tx = optax.adam(learning_rate)
        opt_state = tx.init(self.params)

        @jax.jit
        def train_step(params, opt_state, batch, rng):
            def loss_fn(p):
                recon, mu, logvar = self.module.apply(p, batch, rng)
                mse = jnp.mean((batch - recon) ** 2)
                kl = -0.5 * jnp.mean(1 + logvar - mu**2 - jnp.exp(logvar))
                return mse + kl_weight * kl

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        rng = jax.random.key(self.seed)
        perm_rng = np.random.default_rng(self.seed)
        losses = []
        for epoch in range(epochs):
            # full pass in minibatches — training must see every sequence,
            # not just the first batch_size rows
            order = perm_rng.permutation(len(X))
            # full batches only: a ragged tail batch would retrace the
            # jitted step with a new shape every epoch
            bs = min(batch_size, len(X))
            epoch_loss = 0.0
            n_batches = 0
            for start in range(0, len(X) - bs + 1, bs):
                rng, step_rng = jax.random.split(rng)
                batch = X[order[start:start + bs]]
                self.params, opt_state, loss = train_step(self.params, opt_state, batch, step_rng)
                epoch_loss += float(loss)
                n_batches += 1
            losses.append(epoch_loss / max(n_batches, 1))
        return losses

    def score(self, X) -> np.ndarray:
        X = np.atleast_2d(np.asarray(X, dtype=np.float32))
        if self.module is None:
            self._build(X.shape[1])
        scores = np.asarray(self._score_jit(self.params, X))
        self._last_scores = scores
        self._last_flags = scores > self.threshold
        return scores

    def predict(self, X, names, meta=None):
        return self.score(X).reshape(-1, 1)

    def transform_input(self, X, names, meta=None):
        self.score(X)
        return X

    def tags(self) -> Dict:
        return {
            "outlier": bool(self._last_flags.any()),
            "outlier_count": int(self._last_flags.sum()),
        }

    def metrics(self) -> List[Dict]:
        out = [gauge_metric("outlier_score_max", float(self._last_scores.max(initial=0.0)))]
        flagged = int(self._last_flags.sum())
        if flagged:
            out.append(counter_metric("outliers_total", float(flagged)))
        return out

    def class_names(self):
        return ["reconstruction_error"]

    def save(self, path: str) -> None:
        from flax import serialization

        with open(path, "wb") as f:
            f.write(serialization.to_bytes(self.params))


class MahalanobisDetector(TPUComponent):
    """Online Mahalanobis-distance outlier scoring.

    Maintains a running mean and covariance of the feature stream
    (Welford-style updates) and scores each row by its Mahalanobis
    distance to the current estimate.  Rows beyond ``threshold`` are
    flagged.
    """

    def __init__(
        self,
        n_features: Optional[int] = None,
        threshold: float = 25.0,
        min_samples: int = 10,
        regularisation: float = 1e-3,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.reg = float(regularisation)
        self._lock = threading.Lock()
        self.n = 0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None  # sum of outer-product deviations
        self._last_scores: np.ndarray = np.array([])
        self._last_flags: np.ndarray = np.array([], dtype=bool)
        self.total_outliers = 0
        if n_features:
            self._init_stats(int(n_features))

    def _init_stats(self, d: int) -> None:
        self.mean = np.zeros(d)
        self.m2 = np.zeros((d, d))

    def _update(self, X: np.ndarray) -> None:
        for row in X:
            self.n += 1
            delta = row - self.mean
            self.mean += delta / self.n
            self.m2 += np.outer(delta, row - self.mean)

    def score(self, X: np.ndarray) -> np.ndarray:
        """Mahalanobis distance (squared) per row against current stats."""
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        with self._lock:
            if self.mean is None:
                self._init_stats(X.shape[1])
            if self.n < max(self.min_samples, 2):
                scores = np.zeros(X.shape[0])
            else:
                cov = self.m2 / (self.n - 1) + self.reg * np.eye(X.shape[1])
                inv = np.linalg.inv(cov)
                diff = X - self.mean
                scores = np.einsum("ij,jk,ik->i", diff, inv, diff)
            self._update(X)
            self._last_scores = scores
            self._last_flags = scores > self.threshold
            self.total_outliers += int(self._last_flags.sum())
        return scores

    # as a MODEL: return scores
    def predict(self, X, names, meta=None):
        return self.score(X).reshape(-1, 1)

    # as an input TRANSFORMER: pass through, tag + count
    def transform_input(self, X, names, meta=None):
        self.score(X)
        return X

    def tags(self) -> Dict:
        return {
            "outlier": bool(self._last_flags.any()),
            "outlier_count": int(self._last_flags.sum()),
        }

    def metrics(self) -> List[Dict]:
        out = [gauge_metric("outlier_score_max", float(self._last_scores.max(initial=0.0)))]
        flagged = int(self._last_flags.sum())
        if flagged:
            out.append(counter_metric("outliers_total", float(flagged)))
        return out

    def class_names(self):
        return ["outlier_score"]

    def checkpoint_state(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if self.mean is None:
                return None
            return {
                "n": self.n,
                "mean": self.mean.copy(),
                "m2": self.m2.copy(),
                "total_outliers": self.total_outliers,
            }

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.n = int(state["n"])
            self.mean = np.asarray(state["mean"], dtype=np.float64)
            self.m2 = np.asarray(state["m2"], dtype=np.float64)
            self.total_outliers = int(state.get("total_outliers", 0))


class IsolationForestDetector(TPUComponent):
    """Isolation-forest outlier scoring (reference analogue:
    components/outlier-detection/isolation-forest/CoreIsolationForest.py:8-120,
    a pickled sklearn model).

    Re-designed TPU-first instead of wrapping sklearn: ``fit`` builds
    the random trees on host (tree construction is inherently
    sequential) but packs every tree into flat arrays
    (feature/threshold/child/size per node), so scoring is one jitted
    level-synchronous traversal — rows x trees advance together through
    ``lax.fori_loop`` with no Python recursion and a single device
    launch per batch.

    Score: the standard iForest anomaly score ``2^(-E[h(x)]/c(n))`` in
    (0, 1]; rows with score > ``threshold`` flag as outliers (0.5 is
    the classic "no structure" midpoint).
    """

    def __init__(
        self,
        n_trees: int = 100,
        subsample: int = 256,
        threshold: float = 0.6,
        seed: int = 0,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.n_trees = int(n_trees)
        self.subsample = int(subsample)
        self.threshold = float(threshold)
        self.seed = int(seed)
        # packed forest: (n_trees, max_nodes) arrays
        self.features = None
        self.thresholds = None
        self.left = None
        self.right = None
        self.node_size = None
        self.sample_size = 0
        self._score_jit = None
        self._last_scores = np.array([])
        self._last_flags = np.array([], dtype=bool)
        self._lock = threading.Lock()

    # ---- training (host) --------------------------------------------------

    def fit(self, X: np.ndarray) -> "IsolationForestDetector":
        X = np.atleast_2d(np.asarray(X, dtype=np.float32))
        n, d = X.shape
        rng = np.random.default_rng(self.seed)
        psi = min(self.subsample, n)
        max_depth = int(np.ceil(np.log2(max(psi, 2))))
        max_nodes = 2 ** (max_depth + 1) - 1

        feats = np.zeros((self.n_trees, max_nodes), np.int32)
        thresh = np.zeros((self.n_trees, max_nodes), np.float32)
        left = np.full((self.n_trees, max_nodes), -1, np.int32)
        right = np.full((self.n_trees, max_nodes), -1, np.int32)
        sizes = np.zeros((self.n_trees, max_nodes), np.float32)

        for t in range(self.n_trees):
            sample = X[rng.choice(n, size=psi, replace=False)]
            # iterative build: (node_index, rows, depth)
            next_free = [1]  # node 0 is the root
            stack = [(0, sample, 0)]
            while stack:
                node, rows, depth = stack.pop()
                sizes[t, node] = len(rows)
                spread = rows.max(axis=0) - rows.min(axis=0) if len(rows) else 0
                if depth >= max_depth or len(rows) <= 1 or np.all(spread == 0):
                    continue  # leaf: children stay -1
                f = int(rng.integers(0, d))
                lo, hi = rows[:, f].min(), rows[:, f].max()
                if lo == hi:  # degenerate split axis; try the widest
                    f = int(np.argmax(spread))
                    lo, hi = rows[:, f].min(), rows[:, f].max()
                s = float(rng.uniform(lo, hi))
                mask = rows[:, f] < s
                li, ri = next_free[0], next_free[0] + 1
                next_free[0] += 2
                feats[t, node], thresh[t, node] = f, s
                left[t, node], right[t, node] = li, ri
                stack.append((li, rows[mask], depth + 1))
                stack.append((ri, rows[~mask], depth + 1))

        with self._lock:
            self.features, self.thresholds = feats, thresh
            self.left, self.right, self.node_size = left, right, sizes
            self.sample_size = psi
            self._score_jit = None  # rebuilt lazily against new arrays
        return self

    # ---- scoring (device) -------------------------------------------------

    @staticmethod
    def _avg_path(n):
        """c(n): average unsuccessful-search path length in a BST."""
        import jax.numpy as jnp

        n = jnp.maximum(n, 2.0)
        harmonic = jnp.log(n - 1.0) + 0.5772156649
        return 2.0 * harmonic - 2.0 * (n - 1.0) / n

    def _build_score(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        feats = jnp.asarray(self.features)
        thresh = jnp.asarray(self.thresholds)
        left = jnp.asarray(self.left)
        right = jnp.asarray(self.right)
        sizes = jnp.asarray(self.node_size)
        max_depth = int(np.ceil(np.log2(max(self.sample_size, 2))))
        c_psi = float(np.asarray(self._avg_path(jnp.asarray(float(self.sample_size)))))

        def score(X):
            n_rows = X.shape[0]
            n_trees = feats.shape[0]
            # level-synchronous traversal: every (row, tree) pair walks
            # one level per iteration — a fixed-trip-count loop XLA maps
            # to pure gathers, no data-dependent control flow
            node = jnp.zeros((n_rows, n_trees), jnp.int32)
            depth = jnp.zeros((n_rows, n_trees), jnp.float32)

            def step(_, carry):
                node, depth = carry
                f = jnp.take_along_axis(feats[None, :, :], node[:, :, None], axis=2)[:, :, 0]
                s = jnp.take_along_axis(thresh[None, :, :], node[:, :, None], axis=2)[:, :, 0]
                l = jnp.take_along_axis(left[None, :, :], node[:, :, None], axis=2)[:, :, 0]
                r = jnp.take_along_axis(right[None, :, :], node[:, :, None], axis=2)[:, :, 0]
                x_f = jnp.take_along_axis(X[:, None, :].repeat(n_trees, 1), f[:, :, None], axis=2)[:, :, 0]
                is_leaf = l < 0
                nxt = jnp.where(x_f < s, l, r)
                node = jnp.where(is_leaf, node, nxt)
                depth = jnp.where(is_leaf, depth, depth + 1.0)
                return node, depth

            node, depth = lax.fori_loop(0, max_depth + 1, step, (node, depth))
            leaf_n = jnp.take_along_axis(sizes[None, :, :], node[:, :, None], axis=2)[:, :, 0]
            # unresolved subtrees contribute the BST average path length
            h = depth + jnp.where(leaf_n > 1.0, self._avg_path(leaf_n), 0.0)
            return jnp.power(2.0, -jnp.mean(h, axis=1) / c_psi)

        self._score_jit = jax.jit(score)

    def score(self, X) -> np.ndarray:
        X = np.atleast_2d(np.asarray(X, dtype=np.float32))
        with self._lock:
            if self.features is None:
                raise RuntimeError("IsolationForestDetector.fit was never called")
            if self._score_jit is None:
                self._build_score()
            score_jit = self._score_jit
        scores = np.asarray(score_jit(X))
        self._last_scores = scores
        self._last_flags = scores > self.threshold
        return scores

    # ---- node-role surface ------------------------------------------------

    def predict(self, X, names, meta=None):
        return self.score(X).reshape(-1, 1)

    def transform_input(self, X, names, meta=None):
        self.score(X)
        return X

    def tags(self) -> Dict:
        return {
            "outlier": bool(self._last_flags.any()),
            "outlier_count": int(self._last_flags.sum()),
        }

    def metrics(self) -> List[Dict]:
        out = [gauge_metric("outlier_score_max", float(self._last_scores.max(initial=0.0)))]
        flagged = int(self._last_flags.sum())
        if flagged:
            out.append(counter_metric("outliers_total", float(flagged)))
        return out

    def class_names(self):
        return ["anomaly_score"]

    # ---- persistence (explicit state, pickle-free) ------------------------

    def checkpoint_state(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if self.features is None:
                return None
            return {
                "features": self.features.copy(),
                "thresholds": self.thresholds.copy(),
                "left": self.left.copy(),
                "right": self.right.copy(),
                "node_size": self.node_size.copy(),
                "sample_size": self.sample_size,
            }

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.features = np.asarray(state["features"], np.int32)
            self.thresholds = np.asarray(state["thresholds"], np.float32)
            self.left = np.asarray(state["left"], np.int32)
            self.right = np.asarray(state["right"], np.int32)
            self.node_size = np.asarray(state["node_size"], np.float32)
            self.sample_size = int(state["sample_size"])
            self._score_jit = None


class Seq2SeqOutlierDetector(TPUComponent):
    """Sequence outlier detection via LSTM encoder-decoder
    reconstruction (reference analogue:
    components/outlier-detection/seq2seq-lstm/model.py:6-100 +
    CoreSeq2SeqLSTM.py:10-200, a Keras bidirectional seq2seq decoded
    step-by-step in Python).

    TPU re-design: a flax ``nn.RNN``/LSTM encoder whose final carry
    seeds the decoder, reconstructing the (teacher-forced, one-step
    shifted) sequence in a single ``lax.scan`` — the whole score is one
    XLA program, no per-timestep Python loop.  Score: per-sequence mean
    squared reconstruction error; sequences above ``threshold`` flag as
    outliers (the reference thresholds the same MSE, default 0.003).
    """

    def __init__(
        self,
        n_features: int = 0,
        hidden_dim: int = 32,
        threshold: float = 0.003,
        model_uri: str = "",
        seed: int = 0,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.n_features = int(n_features)
        self.hidden_dim = int(hidden_dim)
        self.threshold = float(threshold)
        self.model_uri = model_uri
        self.seed = int(seed)
        self.module = None
        self.params = None
        self._score_jit = None
        self._last_scores = np.array([])
        self._last_flags = np.array([], dtype=bool)

    def _build(self, n_features: int):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        hidden = self.hidden_dim

        class Seq2Seq(nn.Module):
            @nn.compact
            def __call__(self, x):  # x: (batch, time, features)
                enc = nn.RNN(nn.OptimizedLSTMCell(hidden), return_carry=True, name="encoder")
                carry, _ = enc(x)
                # teacher forcing: decoder sees the sequence shifted one
                # step right (first input is zeros), seeded with the
                # encoder's final state — reconstruction must come from
                # the learned dynamics, not identity copying
                shifted = jnp.concatenate([jnp.zeros_like(x[:, :1]), x[:, :-1]], axis=1)
                dec = nn.RNN(nn.OptimizedLSTMCell(hidden), name="decoder")
                hidden_seq = dec(shifted, initial_carry=carry)
                return nn.Dense(n_features, name="out")(hidden_seq)

        self.n_features = n_features
        self.module = Seq2Seq()
        self.params = self.module.init(
            jax.random.key(self.seed), jnp.zeros((1, 2, n_features))
        )

        def score_fn(params, x):
            recon = self.module.apply(params, x)
            return jnp.mean((x - recon) ** 2, axis=(1, 2))

        self._score_jit = jax.jit(score_fn)

    def load(self) -> None:
        if self.model_uri:
            from flax import serialization

            from seldon_core_tpu.utils import storage

            if self.module is None:
                if not self.n_features:
                    raise ValueError("Seq2SeqOutlierDetector needs n_features with model_uri")
                self._build(self.n_features)
            path = storage.download(self.model_uri)
            with open(path, "rb") as f:
                self.params = serialization.from_bytes(self.params, f.read())

    def fit(self, X: np.ndarray, epochs: int = 50, learning_rate: float = 1e-2,
            batch_size: int = 64) -> List[float]:
        """Train on normal sequences (n, time, features); returns losses."""
        import jax
        import jax.numpy as jnp
        import optax

        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 2:  # single-feature sequences (n, time)
            X = X[:, :, None]
        if self.module is None:
            self._build(X.shape[2])
        tx = optax.adam(learning_rate)
        opt_state = tx.init(self.params)

        @jax.jit
        def train_step(params, opt_state, batch):
            def loss_fn(p):
                recon = self.module.apply(p, batch)
                return jnp.mean((batch - recon) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state2 = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state2, loss

        perm_rng = np.random.default_rng(self.seed)
        losses = []
        for _ in range(epochs):
            order = perm_rng.permutation(len(X))
            bs = min(batch_size, len(X))  # full batches only (no retrace)
            epoch_loss, n_batches = 0.0, 0
            for start in range(0, len(X) - bs + 1, bs):
                self.params, opt_state, loss = train_step(
                    self.params, opt_state, X[order[start:start + bs]]
                )
                epoch_loss += float(loss)
                n_batches += 1
            losses.append(epoch_loss / max(n_batches, 1))
        return losses

    def score(self, X) -> np.ndarray:
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 2:
            X = X[:, :, None]
        if self.module is None:
            raise RuntimeError("Seq2SeqOutlierDetector needs fit() or model_uri before scoring")
        scores = np.asarray(self._score_jit(self.params, X))
        self._last_scores = scores
        self._last_flags = scores > self.threshold
        return scores

    def predict(self, X, names, meta=None):
        return self.score(X).reshape(-1, 1)

    def transform_input(self, X, names, meta=None):
        self.score(X)
        return X

    def tags(self) -> Dict:
        return {
            "outlier": bool(self._last_flags.any()),
            "outlier_count": int(self._last_flags.sum()),
        }

    def metrics(self) -> List[Dict]:
        out = [gauge_metric("outlier_score_max", float(self._last_scores.max(initial=0.0)))]
        flagged = int(self._last_flags.sum())
        if flagged:
            out.append(counter_metric("outliers_total", float(flagged)))
        return out

    def class_names(self):
        return ["reconstruction_error"]

    def save(self, path: str) -> None:
        from flax import serialization

        with open(path, "wb") as f:
            f.write(serialization.to_bytes(self.params))
