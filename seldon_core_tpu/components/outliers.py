"""Online outlier detection components.

Dual-use components like the reference's detectors
(reference: components/outlier-detection/mahalanobis/
CoreMahalanobis.py:7-50): deployable as a MODEL (returns outlier
scores) or as an input TRANSFORMER (passes data through unchanged while
tagging outliers in ``meta.tags`` and counting them in custom metrics).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.runtime.component import TPUComponent, counter_metric, gauge_metric


class MahalanobisDetector(TPUComponent):
    """Online Mahalanobis-distance outlier scoring.

    Maintains a running mean and covariance of the feature stream
    (Welford-style updates) and scores each row by its Mahalanobis
    distance to the current estimate.  Rows beyond ``threshold`` are
    flagged.
    """

    def __init__(
        self,
        n_features: Optional[int] = None,
        threshold: float = 25.0,
        min_samples: int = 10,
        regularisation: float = 1e-3,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.reg = float(regularisation)
        self._lock = threading.Lock()
        self.n = 0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None  # sum of outer-product deviations
        self._last_scores: np.ndarray = np.array([])
        self._last_flags: np.ndarray = np.array([], dtype=bool)
        self.total_outliers = 0
        if n_features:
            self._init_stats(int(n_features))

    def _init_stats(self, d: int) -> None:
        self.mean = np.zeros(d)
        self.m2 = np.zeros((d, d))

    def _update(self, X: np.ndarray) -> None:
        for row in X:
            self.n += 1
            delta = row - self.mean
            self.mean += delta / self.n
            self.m2 += np.outer(delta, row - self.mean)

    def score(self, X: np.ndarray) -> np.ndarray:
        """Mahalanobis distance (squared) per row against current stats."""
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        with self._lock:
            if self.mean is None:
                self._init_stats(X.shape[1])
            if self.n < max(self.min_samples, 2):
                scores = np.zeros(X.shape[0])
            else:
                cov = self.m2 / (self.n - 1) + self.reg * np.eye(X.shape[1])
                inv = np.linalg.inv(cov)
                diff = X - self.mean
                scores = np.einsum("ij,jk,ik->i", diff, inv, diff)
            self._update(X)
            self._last_scores = scores
            self._last_flags = scores > self.threshold
            self.total_outliers += int(self._last_flags.sum())
        return scores

    # as a MODEL: return scores
    def predict(self, X, names, meta=None):
        return self.score(X).reshape(-1, 1)

    # as an input TRANSFORMER: pass through, tag + count
    def transform_input(self, X, names, meta=None):
        self.score(X)
        return X

    def tags(self) -> Dict:
        return {
            "outlier": bool(self._last_flags.any()),
            "outlier_count": int(self._last_flags.sum()),
        }

    def metrics(self) -> List[Dict]:
        out = [gauge_metric("outlier_score_max", float(self._last_scores.max(initial=0.0)))]
        flagged = int(self._last_flags.sum())
        if flagged:
            out.append(counter_metric("outliers_total", float(flagged)))
        return out

    def class_names(self):
        return ["outlier_score"]

    def checkpoint_state(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if self.mean is None:
                return None
            return {
                "n": self.n,
                "mean": self.mean.copy(),
                "m2": self.m2.copy(),
                "total_outliers": self.total_outliers,
            }

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.n = int(state["n"])
            self.mean = np.asarray(state["mean"], dtype=np.float64)
            self.m2 = np.asarray(state["m2"], dtype=np.float64)
            self.total_outliers = int(state.get("total_outliers", 0))
