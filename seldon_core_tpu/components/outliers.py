"""Online outlier detection components.

Dual-use components like the reference's detectors
(reference: components/outlier-detection/mahalanobis/
CoreMahalanobis.py:7-50): deployable as a MODEL (returns outlier
scores) or as an input TRANSFORMER (passes data through unchanged while
tagging outliers in ``meta.tags`` and counting them in custom metrics).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.runtime.component import TPUComponent, counter_metric, gauge_metric


class VAEOutlierDetector(TPUComponent):
    """Variational-autoencoder outlier detection (reference analogue:
    components/outlier-detection/vae/CoreVAE.py:11-170, a Keras model
    with a train.py — here a flax model trained with a jit-compiled
    step on the same device mesh serving uses).

    Scoring: reconstruction error (MSE) of the encoded/decoded input;
    rows above ``threshold`` flag as outliers.  Train with ``fit`` on
    normal data before deploying, or load trained params via
    ``model_uri`` (flax msgpack).
    """

    def __init__(
        self,
        n_features: int = 0,
        latent_dim: int = 2,
        hidden_dim: int = 32,
        threshold: float = 0.5,
        model_uri: str = "",
        seed: int = 0,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.n_features = int(n_features)
        self.latent_dim = int(latent_dim)
        self.hidden_dim = int(hidden_dim)
        self.threshold = float(threshold)
        self.model_uri = model_uri
        self.seed = int(seed)
        self.module = None
        self.params = None
        self._score_jit = None
        self._last_scores = np.array([])
        self._last_flags = np.array([], dtype=bool)

    def _build(self, n_features: int):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        latent, hidden = self.latent_dim, self.hidden_dim

        class VAE(nn.Module):
            @nn.compact
            def __call__(self, x, rng):
                h = nn.relu(nn.Dense(hidden, name="enc1")(x))
                mu = nn.Dense(latent, name="mu")(h)
                logvar = nn.Dense(latent, name="logvar")(h)
                eps = jax.random.normal(rng, mu.shape)
                z = mu + jnp.exp(0.5 * logvar) * eps
                h2 = nn.relu(nn.Dense(hidden, name="dec1")(z))
                recon = nn.Dense(n_features, name="out")(h2)
                return recon, mu, logvar

        self.n_features = n_features
        self.module = VAE()
        import jax

        self.params = self.module.init(
            jax.random.key(self.seed), jnp.zeros((1, n_features)), jax.random.key(0)
        )

        def score_fn(params, x):
            recon, _, _ = self.module.apply(params, x, jax.random.key(0))
            return jnp.mean((x - recon) ** 2, axis=-1)

        self._score_jit = jax.jit(score_fn)

    def load(self) -> None:
        if self.model_uri:
            import jax

            from flax import serialization

            from seldon_core_tpu.utils import storage

            if self.module is None:
                if not self.n_features:
                    raise ValueError("VAEOutlierDetector needs n_features with model_uri")
                self._build(self.n_features)
            path = storage.download(self.model_uri)
            with open(path, "rb") as f:
                self.params = serialization.from_bytes(self.params, f.read())

    def fit(self, X: np.ndarray, epochs: int = 50, learning_rate: float = 1e-2,
            kl_weight: float = 1e-3, batch_size: int = 128) -> List[float]:
        """Train on normal data; returns per-epoch losses."""
        import jax
        import jax.numpy as jnp
        import optax

        X = np.asarray(X, dtype=np.float32)
        if self.module is None:
            self._build(X.shape[1])
        tx = optax.adam(learning_rate)
        opt_state = tx.init(self.params)

        @jax.jit
        def train_step(params, opt_state, batch, rng):
            def loss_fn(p):
                recon, mu, logvar = self.module.apply(p, batch, rng)
                mse = jnp.mean((batch - recon) ** 2)
                kl = -0.5 * jnp.mean(1 + logvar - mu**2 - jnp.exp(logvar))
                return mse + kl_weight * kl

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        rng = jax.random.key(self.seed)
        losses = []
        for epoch in range(epochs):
            rng, step_rng = jax.random.split(rng)
            batch = X[:batch_size]
            self.params, opt_state, loss = train_step(self.params, opt_state, batch, step_rng)
            losses.append(float(loss))
        return losses

    def score(self, X) -> np.ndarray:
        X = np.atleast_2d(np.asarray(X, dtype=np.float32))
        if self.module is None:
            self._build(X.shape[1])
        scores = np.asarray(self._score_jit(self.params, X))
        self._last_scores = scores
        self._last_flags = scores > self.threshold
        return scores

    def predict(self, X, names, meta=None):
        return self.score(X).reshape(-1, 1)

    def transform_input(self, X, names, meta=None):
        self.score(X)
        return X

    def tags(self) -> Dict:
        return {
            "outlier": bool(self._last_flags.any()),
            "outlier_count": int(self._last_flags.sum()),
        }

    def metrics(self) -> List[Dict]:
        out = [gauge_metric("outlier_score_max", float(self._last_scores.max(initial=0.0)))]
        flagged = int(self._last_flags.sum())
        if flagged:
            out.append(counter_metric("outliers_total", float(flagged)))
        return out

    def class_names(self):
        return ["reconstruction_error"]

    def save(self, path: str) -> None:
        from flax import serialization

        with open(path, "wb") as f:
            f.write(serialization.to_bytes(self.params))


class MahalanobisDetector(TPUComponent):
    """Online Mahalanobis-distance outlier scoring.

    Maintains a running mean and covariance of the feature stream
    (Welford-style updates) and scores each row by its Mahalanobis
    distance to the current estimate.  Rows beyond ``threshold`` are
    flagged.
    """

    def __init__(
        self,
        n_features: Optional[int] = None,
        threshold: float = 25.0,
        min_samples: int = 10,
        regularisation: float = 1e-3,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.reg = float(regularisation)
        self._lock = threading.Lock()
        self.n = 0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None  # sum of outer-product deviations
        self._last_scores: np.ndarray = np.array([])
        self._last_flags: np.ndarray = np.array([], dtype=bool)
        self.total_outliers = 0
        if n_features:
            self._init_stats(int(n_features))

    def _init_stats(self, d: int) -> None:
        self.mean = np.zeros(d)
        self.m2 = np.zeros((d, d))

    def _update(self, X: np.ndarray) -> None:
        for row in X:
            self.n += 1
            delta = row - self.mean
            self.mean += delta / self.n
            self.m2 += np.outer(delta, row - self.mean)

    def score(self, X: np.ndarray) -> np.ndarray:
        """Mahalanobis distance (squared) per row against current stats."""
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        with self._lock:
            if self.mean is None:
                self._init_stats(X.shape[1])
            if self.n < max(self.min_samples, 2):
                scores = np.zeros(X.shape[0])
            else:
                cov = self.m2 / (self.n - 1) + self.reg * np.eye(X.shape[1])
                inv = np.linalg.inv(cov)
                diff = X - self.mean
                scores = np.einsum("ij,jk,ik->i", diff, inv, diff)
            self._update(X)
            self._last_scores = scores
            self._last_flags = scores > self.threshold
            self.total_outliers += int(self._last_flags.sum())
        return scores

    # as a MODEL: return scores
    def predict(self, X, names, meta=None):
        return self.score(X).reshape(-1, 1)

    # as an input TRANSFORMER: pass through, tag + count
    def transform_input(self, X, names, meta=None):
        self.score(X)
        return X

    def tags(self) -> Dict:
        return {
            "outlier": bool(self._last_flags.any()),
            "outlier_count": int(self._last_flags.sum()),
        }

    def metrics(self) -> List[Dict]:
        out = [gauge_metric("outlier_score_max", float(self._last_scores.max(initial=0.0)))]
        flagged = int(self._last_flags.sum())
        if flagged:
            out.append(counter_metric("outliers_total", float(flagged)))
        return out

    def class_names(self):
        return ["outlier_score"]

    def checkpoint_state(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if self.mean is None:
                return None
            return {
                "n": self.n,
                "mean": self.mean.copy(),
                "m2": self.m2.copy(),
                "total_outliers": self.total_outliers,
            }

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.n = int(state["n"])
            self.mean = np.asarray(state["mean"], dtype=np.float64)
            self.m2 = np.asarray(state["m2"], dtype=np.float64)
            self.total_outliers = int(state.get("total_outliers", 0))
