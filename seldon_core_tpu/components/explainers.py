"""Model explainers.

The reference deploys a *separate* alibi explainer container per
predictor, reached via an ``:explain`` URL
(reference: operator/controllers/seldondeployment_explainers.go:33-196,
client explain_predict_gateway seldon_client.py:1542).  TPU-native
explanation is cheaper and tighter: for jax-served models the explainer
shares the predictor's process and HBM-resident parameters, and
gradient-based attribution is one more jit-compiled XLA program on the
same chip.

* ``IntegratedGradientsExplainer`` — path-integrated gradients for any
  flax module served by JaxServer (white-box, exact, fast on MXU).
* ``PermutationExplainer`` — model-agnostic per-feature importance by
  column permutation (works for any component, including torch/sklearn
  nodes).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent


class IntegratedGradientsExplainer(TPUComponent):
    """Integrated gradients along the straight path from a baseline.

    attribution_j = (x_j - b_j) * mean_k d f_target / d x_j evaluated at
    b + (k/m)(x - b).  The whole computation (interpolation, vmap'd
    grads, reduction) is one jit program.
    """

    def __init__(
        self,
        model: Any = None,  # a JaxServer (or anything with .module/.variables)
        steps: int = 16,
        baseline: str = "zeros",  # zeros | mean
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model = model
        self.steps = int(steps)
        self.baseline = baseline
        self._explain_jit = None

    def attach(self, model: Any) -> None:
        self.model = model
        self._explain_jit = None

    def load(self) -> None:
        if self.model is None:
            raise MicroserviceError(
                "IntegratedGradientsExplainer needs a jax model to attach to",
                status_code=400,
                reason="NO_MODEL",
            )
        if getattr(self.model, "module", None) is None and hasattr(self.model, "load"):
            self.model.load()
        import jax
        import jax.numpy as jnp

        module = self.model.module
        variables = self.model.variables
        steps = self.steps

        def target_score(x, target):
            logits = module.apply(variables, x[None])
            return logits[0, target]

        grad_fn = jax.grad(target_score)

        def explain_one(x, baseline):
            alphas = jnp.linspace(1.0 / steps, 1.0, steps)
            logits = module.apply(variables, x[None])
            target = jnp.argmax(logits[0])

            def point_grad(alpha):
                return grad_fn(baseline + alpha * (x - baseline), target)

            grads = jax.vmap(point_grad)(alphas)
            attribution = (x - baseline) * jnp.mean(grads, axis=0)
            return attribution, target, logits[0, target]

        self._explain_jit = jax.jit(jax.vmap(explain_one, in_axes=(0, None)))

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self._explain_jit is None:
            self.load()
        import jax.numpy as jnp

        X = np.asarray(X, dtype=np.float32)
        if X.ndim == len(self.model.input_shape):
            X = X[None]
        baseline = jnp.zeros(X.shape[1:], jnp.float32)
        if self.baseline == "mean":
            baseline = jnp.asarray(X.mean(axis=0))
        attributions, targets, scores = self._explain_jit(jnp.asarray(X), baseline)
        return {
            "method": "integrated_gradients",
            "attributions": np.asarray(attributions, dtype=np.float64).tolist(),
            "targets": np.asarray(targets).tolist(),
            "scores": np.asarray(scores, dtype=np.float64).tolist(),
            "names": list(names or []),
        }

    # deployable as a MODEL node: predict returns attributions
    def predict(self, X, names, meta=None):
        return np.asarray(self.explain(X, names)["attributions"])


class PermutationExplainer(TPUComponent):
    """Per-feature importance by column permutation (black-box).

    importance_j = mean |f(X) - f(X with column j shuffled)| — model
    agnostic, needs only the component's predict.
    """

    def __init__(self, model: Any = None, n_repeats: int = 4, seed: int = 0, **kwargs: Any):
        super().__init__(**kwargs)
        self.model = model
        self.n_repeats = int(n_repeats)
        self._rng = np.random.default_rng(seed)

    def attach(self, model: Any) -> None:
        self.model = model

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self.model is None:
            raise MicroserviceError("PermutationExplainer needs a model", status_code=400, reason="NO_MODEL")
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        base = np.asarray(self.model.predict(X, list(names or [])))
        n_features = X.shape[1]
        importances = np.zeros(n_features)
        for j in range(n_features):
            deltas = []
            for _ in range(self.n_repeats):
                Xp = X.copy()
                self._rng.shuffle(Xp[:, j])
                out = np.asarray(self.model.predict(Xp, list(names or [])))
                deltas.append(np.abs(base - out).mean())
            importances[j] = float(np.mean(deltas))
        return {
            "method": "permutation_importance",
            "importances": importances.tolist(),
            "names": list(names or []),
        }

    def predict(self, X, names, meta=None):
        return np.asarray(self.explain(X, names)["importances"])[None, :]


EXPLAINER_TYPES: Dict[str, Callable[..., Any]] = {
    "integrated_gradients": IntegratedGradientsExplainer,
    "permutation": PermutationExplainer,
}


def build_explainer(config: Dict[str, Any]) -> Any:
    etype = config.get("type", "integrated_gradients")
    factory = EXPLAINER_TYPES.get(etype)
    if factory is None:
        raise MicroserviceError(f"unknown explainer type {etype!r}", status_code=400, reason="UNKNOWN_EXPLAINER")
    params = {k: v for k, v in config.items() if k != "type"}
    return factory(**params)
