"""Model explainers.

The reference deploys a *separate* alibi explainer container per
predictor, reached via an ``:explain`` URL
(reference: operator/controllers/seldondeployment_explainers.go:33-196,
client explain_predict_gateway seldon_client.py:1542).  TPU-native
explanation is cheaper and tighter: for jax-served models the explainer
shares the predictor's process and HBM-resident parameters, and
gradient-based attribution is one more jit-compiled XLA program on the
same chip.

* ``IntegratedGradientsExplainer`` — path-integrated gradients for any
  flax module served by JaxServer (white-box, exact, fast on MXU).
* ``PermutationExplainer`` — model-agnostic per-feature importance by
  column permutation (works for any component, including torch/sklearn
  nodes).
* ``KernelShapExplainer`` — model-agnostic Shapley values via the
  KernelSHAP weighted regression (the estimator behind the reference's
  alibi KernelShap explainer option).  TPU-first shape: every sampled
  coalition becomes one row of ONE batched predict (rides the dynamic
  batcher / one XLA call); the weighted least squares is a tiny
  (M−1)² host-side float64 solve, factored once per call.  With few
  features all coalitions are enumerated, making the values exact.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent


class IntegratedGradientsExplainer(TPUComponent):
    """Integrated gradients along the straight path from a baseline.

    attribution_j = (x_j - b_j) * mean_k d f_target / d x_j evaluated at
    b + (k/m)(x - b).  The whole computation (interpolation, vmap'd
    grads, reduction) is one jit program.
    """

    def __init__(
        self,
        model: Any = None,  # a JaxServer (or anything with .module/.variables)
        steps: int = 16,
        baseline: str = "zeros",  # zeros | mean
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model = model
        self.steps = int(steps)
        self.baseline = baseline
        self._explain_jit = None

    def attach(self, model: Any) -> None:
        self.model = model
        self._explain_jit = None

    def load(self) -> None:
        if self.model is None:
            raise MicroserviceError(
                "IntegratedGradientsExplainer needs a jax model to attach to",
                status_code=400,
                reason="NO_MODEL",
            )
        if getattr(self.model, "module", None) is None and hasattr(self.model, "load"):
            self.model.load()
        import jax
        import jax.numpy as jnp

        module = self.model.module
        variables = self.model.variables
        steps = self.steps

        def target_score(x, target):
            logits = module.apply(variables, x[None])
            return logits[0, target]

        grad_fn = jax.grad(target_score)

        def explain_one(x, baseline):
            alphas = jnp.linspace(1.0 / steps, 1.0, steps)
            logits = module.apply(variables, x[None])
            target = jnp.argmax(logits[0])

            def point_grad(alpha):
                return grad_fn(baseline + alpha * (x - baseline), target)

            grads = jax.vmap(point_grad)(alphas)
            attribution = (x - baseline) * jnp.mean(grads, axis=0)
            return attribution, target, logits[0, target]

        self._explain_jit = jax.jit(jax.vmap(explain_one, in_axes=(0, None)))

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self._explain_jit is None:
            self.load()
        import jax.numpy as jnp

        X = np.asarray(X, dtype=np.float32)
        if X.ndim == len(self.model.input_shape):
            X = X[None]
        baseline = jnp.zeros(X.shape[1:], jnp.float32)
        if self.baseline == "mean":
            baseline = jnp.asarray(X.mean(axis=0))
        attributions, targets, scores = self._explain_jit(jnp.asarray(X), baseline)
        return {
            "method": "integrated_gradients",
            "attributions": np.asarray(attributions, dtype=np.float64).tolist(),
            "targets": np.asarray(targets).tolist(),
            "scores": np.asarray(scores, dtype=np.float64).tolist(),
            "names": list(names or []),
        }

    # deployable as a MODEL node: predict returns attributions
    def predict(self, X, names, meta=None):
        return np.asarray(self.explain(X, names)["attributions"])


class PermutationExplainer(TPUComponent):
    """Per-feature importance by column permutation (black-box).

    importance_j = mean |f(X) - f(X with column j shuffled)| — model
    agnostic, needs only the component's predict.
    """

    def __init__(self, model: Any = None, n_repeats: int = 4, seed: int = 0, **kwargs: Any):
        super().__init__(**kwargs)
        self.model = model
        self.n_repeats = int(n_repeats)
        self._rng = np.random.default_rng(seed)

    def attach(self, model: Any) -> None:
        self.model = model

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self.model is None:
            raise MicroserviceError("PermutationExplainer needs a model", status_code=400, reason="NO_MODEL")
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        base = np.asarray(self.model.predict(X, list(names or [])))
        n_features = X.shape[1]
        importances = np.zeros(n_features)
        for j in range(n_features):
            deltas = []
            for _ in range(self.n_repeats):
                Xp = X.copy()
                self._rng.shuffle(Xp[:, j])
                out = np.asarray(self.model.predict(Xp, list(names or [])))
                deltas.append(np.abs(base - out).mean())
            importances[j] = float(np.mean(deltas))
        return {
            "method": "permutation_importance",
            "importances": importances.tolist(),
            "names": list(names or []),
        }

    def predict(self, X, names, meta=None):
        return np.asarray(self.explain(X, names)["importances"])[None, :]


class KernelShapExplainer(TPUComponent):
    """Shapley values by KernelSHAP weighted regression (black-box).

    For instance ``x`` with baseline ``b``, coalition ``z ∈ {0,1}^M``
    maps to the masked input ``z·x + (1−z)·b``; the model is evaluated
    on ALL coalitions in one batched predict, then attributions solve
    the Shapley-kernel-weighted least squares (host-side float64,
    factored once per call) with the efficiency constraint
    ``Σφ = f(x) − f(b)`` enforced by substitution.

    When ``2^M − 2 <= n_samples`` every coalition is enumerated and the
    result is the exact Shapley value; otherwise coalitions are sampled
    in complement pairs, sizes drawn ∝ (M−1)/(s(M−s)) (the kernel's
    size profile, so the regression weights stay uniform).

    ``baseline``: "zeros", "mean" (column means of the explained batch),
    or pass ``background`` — rows of reference data whose column means
    become the baseline (what "mean" should be for single-instance
    explain calls).
    """

    def __init__(
        self,
        model: Any = None,
        n_samples: int = 256,
        baseline: str = "zeros",  # zeros | mean
        background: Optional[Any] = None,  # reference rows (list or array)
        seed: int = 0,
        ridge: float = 1e-6,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model = model
        self.n_samples = int(n_samples)
        if self.n_samples < 4:
            raise MicroserviceError(
                "kernel SHAP needs n_samples >= 4 (got "
                f"{self.n_samples}) — fewer coalitions cannot support the regression",
                status_code=400,
                reason="BAD_REQUEST",
            )
        self.baseline = baseline
        self.background = None if background is None else np.atleast_2d(np.asarray(background, np.float64))
        self.seed = int(seed)
        self.ridge = float(ridge)

    def attach(self, model: Any) -> None:
        self.model = model

    # ---- coalition design -------------------------------------------------

    def _coalitions(self, m: int, rng: np.random.Generator) -> tuple:
        """(Z, w): coalition matrix (S, m) with 0 < |z| < m, and WLS
        weights.  Exact enumeration when it fits the sample budget."""
        total = 2**m - 2
        if total <= self.n_samples:
            Z = np.array(
                [[(i >> j) & 1 for j in range(m)] for i in range(1, 2**m - 1)],
                dtype=np.float64,
            )
            sizes = Z.sum(axis=1)
            # Shapley kernel: (m-1) / (C(m,s) * s * (m-s))
            from math import comb

            w = (m - 1) / (np.array([comb(m, int(s)) for s in sizes]) * sizes * (m - sizes))
            return Z, w
        # paired sampling; drawing sizes from the kernel's size profile
        # leaves uniform regression weights (importance sampling)
        sizes = np.arange(1, m)
        p = (m - 1) / (sizes * (m - sizes))
        p = p / p.sum()
        n_pairs = self.n_samples // 2
        draw = rng.choice(sizes, size=n_pairs, p=p)
        Z = np.zeros((2 * n_pairs, m))
        for i, s in enumerate(draw):
            idx = rng.choice(m, size=int(s), replace=False)
            Z[2 * i, idx] = 1.0
            Z[2 * i + 1] = 1.0 - Z[2 * i]  # complement pair
        return Z, np.ones(len(Z))

    # ---- the solve --------------------------------------------------------

    def _baseline(self, X: np.ndarray) -> np.ndarray:
        m = X.shape[1]
        if self.background is not None:
            if self.background.shape[1] != m:
                raise MicroserviceError(
                    f"background has {self.background.shape[1]} features, request has {m}",
                    status_code=400,
                    reason="BAD_REQUEST",
                )
            return self.background.mean(axis=0)
        if self.baseline == "mean":
            if len(X) < 2:
                raise MicroserviceError(
                    "baseline='mean' over a single instance collapses to the "
                    "instance itself (all-zero attributions); pass reference "
                    "rows via 'background' or explain a batch",
                    status_code=400,
                    reason="BAD_REQUEST",
                )
            return X.mean(axis=0)
        return np.zeros(m)

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self.model is None:
            raise MicroserviceError("KernelShapExplainer needs a model", status_code=400, reason="NO_MODEL")
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        n_rows, m = X.shape
        if m < 2:
            raise MicroserviceError(
                "kernel SHAP needs at least 2 features", status_code=400, reason="BAD_REQUEST"
            )
        rng = np.random.default_rng(self.seed)
        b = self._baseline(X)
        Z, w = self._coalitions(m, rng)

        # the weighted normal equations share Z/w across every row:
        # factor once (float64 on host — the system is (m-1)^2 tiny;
        # the device's job is the batched coalition forwards, not this)
        A = Z[:, :-1] - Z[:, -1:]  # (S, m-1)
        AtW = A.T * w[None, :]
        lhs = AtW @ A + self.ridge * np.eye(m - 1)

        names = list(names or [])
        targets: List[int] = []
        base_values: List[float] = []
        rhs_cols: List[np.ndarray] = []
        fx_fb: List[tuple] = []
        for x in X:
            # ONE batched predict: [x, b, every masked coalition]
            masked = Z * x[None, :] + (1.0 - Z) * b[None, :]
            batch = np.concatenate([x[None], b[None], masked], axis=0)
            out = np.asarray(self.model.predict(batch, names))
            if out.ndim == 1:
                out = out[:, None]
            target = int(np.argmax(out[0]))
            fx, fb = float(out[0, target]), float(out[1, target])
            y = out[2:, target].astype(np.float64)
            rhs_cols.append(AtW @ (y - fb - Z[:, -1] * (fx - fb)))
            fx_fb.append((fx, fb))
            targets.append(target)
            base_values.append(fb)
        # one multi-RHS solve for the whole batch; efficiency constraint
        # Σφ = fx − fb substituted out (phi_last = (fx−fb) − Σ others)
        phi_head = np.linalg.solve(lhs, np.stack(rhs_cols, axis=1))  # (m-1, n)
        attributions = [
            np.append(phi_head[:, i], (fx - fb) - phi_head[:, i].sum()).tolist()
            for i, (fx, fb) in enumerate(fx_fb)
        ]
        return {
            "method": "kernel_shap",
            "attributions": attributions,
            "targets": targets,
            "base_values": base_values,
            "names": names,
        }

    def predict(self, X, names, meta=None):
        return np.asarray(self.explain(X, names)["attributions"])


EXPLAINER_TYPES: Dict[str, Callable[..., Any]] = {
    "integrated_gradients": IntegratedGradientsExplainer,
    "permutation": PermutationExplainer,
    "kernel_shap": KernelShapExplainer,
}


def build_explainer(config: Dict[str, Any]) -> Any:
    etype = config.get("type", "integrated_gradients")
    factory = EXPLAINER_TYPES.get(etype)
    if factory is None:
        raise MicroserviceError(f"unknown explainer type {etype!r}", status_code=400, reason="UNKNOWN_EXPLAINER")
    params = {k: v for k, v in config.items() if k != "type"}
    return factory(**params)
