"""Model explainers.

The reference deploys a *separate* alibi explainer container per
predictor, reached via an ``:explain`` URL
(reference: operator/controllers/seldondeployment_explainers.go:33-196,
client explain_predict_gateway seldon_client.py:1542).  TPU-native
explanation is cheaper and tighter: for jax-served models the explainer
shares the predictor's process and HBM-resident parameters, and
gradient-based attribution is one more jit-compiled XLA program on the
same chip.

* ``IntegratedGradientsExplainer`` — path-integrated gradients for any
  flax module served by JaxServer (white-box, exact, fast on MXU).
* ``PermutationExplainer`` — model-agnostic per-feature importance by
  column permutation (works for any component, including torch/sklearn
  nodes).
* ``KernelShapExplainer`` — model-agnostic Shapley values via the
  KernelSHAP weighted regression (the estimator behind the reference's
  alibi KernelShap explainer option).  TPU-first shape: every sampled
  coalition becomes one row of ONE batched predict (rides the dynamic
  batcher / one XLA call); the weighted least squares is a tiny
  (M−1)² host-side float64 solve, factored once per call.  With few
  features all coalitions are enumerated, making the values exact.
* ``AnchorsExplainer`` — the flagship method of the reference's ONLY
  wired explainer container (alibi's AnchorTabular — the operator
  defaults ``seldonio/alibiexplainer_grpc``, reference:
  operator/controllers/seldondeployment_explainers.go:57-59): an
  *anchor* is a minimal rule of feature predicates under which the
  model's prediction (almost) never changes — precision
  P(f(z)=f(x) | z ⊨ rule) ≥ threshold.  Tabular search over
  quantile-discretised features; same TPU-first shape as kernel SHAP:
  every candidate rule of a beam round is estimated from perturbation
  rows stacked into ONE batched predict.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent


class IntegratedGradientsExplainer(TPUComponent):
    """Integrated gradients along the straight path from a baseline.

    attribution_j = (x_j - b_j) * mean_k d f_target / d x_j evaluated at
    b + (k/m)(x - b).  The whole computation (interpolation, vmap'd
    grads, reduction) is one jit program.
    """

    def __init__(
        self,
        model: Any = None,  # a JaxServer (or anything with .module/.variables)
        steps: int = 16,
        baseline: str = "zeros",  # zeros | mean
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model = model
        self.steps = int(steps)
        self.baseline = baseline
        self._explain_jit = None

    def attach(self, model: Any) -> None:
        self.model = model
        self._explain_jit = None

    def load(self) -> None:
        if self.model is None:
            raise MicroserviceError(
                "IntegratedGradientsExplainer needs a jax model to attach to",
                status_code=400,
                reason="NO_MODEL",
            )
        if getattr(self.model, "module", None) is None and hasattr(self.model, "load"):
            self.model.load()
        import jax
        import jax.numpy as jnp

        module = self.model.module
        variables = self.model.variables
        steps = self.steps

        def target_score(x, target):
            logits = module.apply(variables, x[None])
            return logits[0, target]

        grad_fn = jax.grad(target_score)

        def explain_one(x, baseline):
            alphas = jnp.linspace(1.0 / steps, 1.0, steps)
            logits = module.apply(variables, x[None])
            target = jnp.argmax(logits[0])

            def point_grad(alpha):
                return grad_fn(baseline + alpha * (x - baseline), target)

            grads = jax.vmap(point_grad)(alphas)
            attribution = (x - baseline) * jnp.mean(grads, axis=0)
            return attribution, target, logits[0, target]

        self._explain_jit = jax.jit(jax.vmap(explain_one, in_axes=(0, None)))

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self._explain_jit is None:
            self.load()
        import jax.numpy as jnp

        X = np.asarray(X, dtype=np.float32)
        if X.ndim == len(self.model.input_shape):
            X = X[None]
        baseline = jnp.zeros(X.shape[1:], jnp.float32)
        if self.baseline == "mean":
            baseline = jnp.asarray(X.mean(axis=0))
        attributions, targets, scores = self._explain_jit(jnp.asarray(X), baseline)
        return {
            "method": "integrated_gradients",
            "attributions": np.asarray(attributions, dtype=np.float64).tolist(),
            "targets": np.asarray(targets).tolist(),
            "scores": np.asarray(scores, dtype=np.float64).tolist(),
            "names": list(names or []),
        }

    # deployable as a MODEL node: predict returns attributions
    def predict(self, X, names, meta=None):
        return np.asarray(self.explain(X, names)["attributions"])


class PermutationExplainer(TPUComponent):
    """Per-feature importance by column permutation (black-box).

    importance_j = mean |f(X) - f(X with column j shuffled)| — model
    agnostic, needs only the component's predict.
    """

    def __init__(self, model: Any = None, n_repeats: int = 4, seed: int = 0, **kwargs: Any):
        super().__init__(**kwargs)
        self.model = model
        self.n_repeats = int(n_repeats)
        self._rng = np.random.default_rng(seed)

    def attach(self, model: Any) -> None:
        self.model = model

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self.model is None:
            raise MicroserviceError("PermutationExplainer needs a model", status_code=400, reason="NO_MODEL")
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        base = np.asarray(self.model.predict(X, list(names or [])))
        n_features = X.shape[1]
        importances = np.zeros(n_features)
        for j in range(n_features):
            deltas = []
            for _ in range(self.n_repeats):
                Xp = X.copy()
                self._rng.shuffle(Xp[:, j])
                out = np.asarray(self.model.predict(Xp, list(names or [])))
                deltas.append(np.abs(base - out).mean())
            importances[j] = float(np.mean(deltas))
        return {
            "method": "permutation_importance",
            "importances": importances.tolist(),
            "names": list(names or []),
        }

    def predict(self, X, names, meta=None):
        return np.asarray(self.explain(X, names)["importances"])[None, :]


class KernelShapExplainer(TPUComponent):
    """Shapley values by KernelSHAP weighted regression (black-box).

    For instance ``x`` with baseline ``b``, coalition ``z ∈ {0,1}^M``
    maps to the masked input ``z·x + (1−z)·b``; the model is evaluated
    on ALL coalitions in one batched predict, then attributions solve
    the Shapley-kernel-weighted least squares (host-side float64,
    factored once per call) with the efficiency constraint
    ``Σφ = f(x) − f(b)`` enforced by substitution.

    When ``2^M − 2 <= n_samples`` every coalition is enumerated and the
    result is the exact Shapley value; otherwise coalitions are sampled
    in complement pairs, sizes drawn ∝ (M−1)/(s(M−s)) (the kernel's
    size profile, so the regression weights stay uniform).

    ``baseline``: "zeros", "mean" (column means of the explained batch),
    or pass ``background`` — rows of reference data whose column means
    become the baseline (what "mean" should be for single-instance
    explain calls).
    """

    def __init__(
        self,
        model: Any = None,
        n_samples: int = 256,
        baseline: str = "zeros",  # zeros | mean
        background: Optional[Any] = None,  # reference rows (list or array)
        seed: int = 0,
        ridge: float = 1e-6,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model = model
        self.n_samples = int(n_samples)
        if self.n_samples < 4:
            raise MicroserviceError(
                "kernel SHAP needs n_samples >= 4 (got "
                f"{self.n_samples}) — fewer coalitions cannot support the regression",
                status_code=400,
                reason="BAD_REQUEST",
            )
        self.baseline = baseline
        self.background = None if background is None else np.atleast_2d(np.asarray(background, np.float64))
        self.seed = int(seed)
        self.ridge = float(ridge)

    def attach(self, model: Any) -> None:
        self.model = model

    # ---- coalition design -------------------------------------------------

    def _coalitions(self, m: int, rng: np.random.Generator) -> tuple:
        """(Z, w): coalition matrix (S, m) with 0 < |z| < m, and WLS
        weights.  Exact enumeration when it fits the sample budget."""
        total = 2**m - 2
        if total <= self.n_samples:
            Z = np.array(
                [[(i >> j) & 1 for j in range(m)] for i in range(1, 2**m - 1)],
                dtype=np.float64,
            )
            sizes = Z.sum(axis=1)
            # Shapley kernel: (m-1) / (C(m,s) * s * (m-s))
            from math import comb

            w = (m - 1) / (np.array([comb(m, int(s)) for s in sizes]) * sizes * (m - sizes))
            return Z, w
        # paired sampling; drawing sizes from the kernel's size profile
        # leaves uniform regression weights (importance sampling)
        sizes = np.arange(1, m)
        p = (m - 1) / (sizes * (m - sizes))
        p = p / p.sum()
        n_pairs = self.n_samples // 2
        draw = rng.choice(sizes, size=n_pairs, p=p)
        Z = np.zeros((2 * n_pairs, m))
        for i, s in enumerate(draw):
            idx = rng.choice(m, size=int(s), replace=False)
            Z[2 * i, idx] = 1.0
            Z[2 * i + 1] = 1.0 - Z[2 * i]  # complement pair
        return Z, np.ones(len(Z))

    # ---- the solve --------------------------------------------------------

    def _baseline(self, X: np.ndarray) -> np.ndarray:
        m = X.shape[1]
        if self.background is not None:
            if self.background.shape[1] != m:
                raise MicroserviceError(
                    f"background has {self.background.shape[1]} features, request has {m}",
                    status_code=400,
                    reason="BAD_REQUEST",
                )
            return self.background.mean(axis=0)
        if self.baseline == "mean":
            if len(X) < 2:
                raise MicroserviceError(
                    "baseline='mean' over a single instance collapses to the "
                    "instance itself (all-zero attributions); pass reference "
                    "rows via 'background' or explain a batch",
                    status_code=400,
                    reason="BAD_REQUEST",
                )
            return X.mean(axis=0)
        return np.zeros(m)

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self.model is None:
            raise MicroserviceError("KernelShapExplainer needs a model", status_code=400, reason="NO_MODEL")
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        n_rows, m = X.shape
        if m < 2:
            raise MicroserviceError(
                "kernel SHAP needs at least 2 features", status_code=400, reason="BAD_REQUEST"
            )
        rng = np.random.default_rng(self.seed)
        b = self._baseline(X)
        Z, w = self._coalitions(m, rng)

        # the weighted normal equations share Z/w across every row:
        # factor once (float64 on host — the system is (m-1)^2 tiny;
        # the device's job is the batched coalition forwards, not this)
        A = Z[:, :-1] - Z[:, -1:]  # (S, m-1)
        AtW = A.T * w[None, :]
        lhs = AtW @ A + self.ridge * np.eye(m - 1)

        names = list(names or [])
        targets: List[int] = []
        base_values: List[float] = []
        rhs_cols: List[np.ndarray] = []
        fx_fb: List[tuple] = []
        for x in X:
            # ONE batched predict: [x, b, every masked coalition]
            masked = Z * x[None, :] + (1.0 - Z) * b[None, :]
            batch = np.concatenate([x[None], b[None], masked], axis=0)
            out = np.asarray(self.model.predict(batch, names))
            if out.ndim == 1:
                out = out[:, None]
            target = int(np.argmax(out[0]))
            fx, fb = float(out[0, target]), float(out[1, target])
            y = out[2:, target].astype(np.float64)
            rhs_cols.append(AtW @ (y - fb - Z[:, -1] * (fx - fb)))
            fx_fb.append((fx, fb))
            targets.append(target)
            base_values.append(fb)
        # one multi-RHS solve for the whole batch; efficiency constraint
        # Σφ = fx − fb substituted out (phi_last = (fx−fb) − Σ others)
        phi_head = np.linalg.solve(lhs, np.stack(rhs_cols, axis=1))  # (m-1, n)
        attributions = [
            np.append(phi_head[:, i], (fx - fb) - phi_head[:, i].sum()).tolist()
            for i, (fx, fb) in enumerate(fx_fb)
        ]
        return {
            "method": "kernel_shap",
            "attributions": attributions,
            "targets": targets,
            "base_values": base_values,
            "names": names,
        }

    def predict(self, X, names, meta=None):
        return np.asarray(self.explain(X, names)["attributions"])


class AnchorsExplainer(TPUComponent):
    """Tabular anchors: minimal high-precision rules (black-box).

    For instance ``x`` with model decision ``t = argmax f(x)``, find
    the smallest predicate set ``A`` (each predicate: "feature j falls
    in x's quantile bin") whose precision
    ``P(argmax f(z) = t | z ⊨ A) ≥ precision_threshold``, where ``z``
    is a background row with the anchored features resampled from the
    bin x occupies.  Greedy beam search over anchor size; every
    candidate of a round is estimated from ``n_samples`` perturbation
    rows, all candidates stacked into ONE batched predict (the same
    device-friendly evaluation shape as KernelShapExplainer — the
    model call count is the round count, not the candidate count).

    ``background`` rows are required (they define both the quantile
    grid and the perturbation distribution — alibi's AnchorTabular
    requires training data for the same reason).  Coverage is the
    fraction of background rows satisfying the rule.

    Result per row: the predicate list (feature index, human-readable
    predicate string, bin bounds), measured precision, coverage, and
    whether the threshold was reached (``raw_precision`` of the best
    effort is reported either way — a model with no compact anchor is
    an honest outcome, not an error).
    """

    def __init__(
        self,
        model: Any = None,
        background: Optional[Any] = None,  # reference rows (list or array)
        n_bins: int = 4,
        precision_threshold: float = 0.95,
        n_samples: int = 128,
        beam_size: int = 2,
        max_anchor_size: Optional[int] = None,
        seed: int = 0,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model = model
        self.background = (
            None if background is None
            else np.atleast_2d(np.asarray(background, np.float64))
        )
        self.n_bins = int(n_bins)
        if self.n_bins < 2:
            raise MicroserviceError(
                "anchors needs n_bins >= 2", status_code=400, reason="BAD_REQUEST"
            )
        self.precision_threshold = float(precision_threshold)
        self.n_samples = int(n_samples)
        self.beam_size = int(beam_size)
        self.max_anchor_size = max_anchor_size
        self.seed = int(seed)
        self._edges: Optional[np.ndarray] = None  # (m, n_bins-1) quantile edges

    def attach(self, model: Any) -> None:
        self.model = model

    # ---- discretisation ---------------------------------------------------

    def _fit_edges(self, m: int) -> np.ndarray:
        if self.background is None:
            raise MicroserviceError(
                "AnchorsExplainer needs 'background' rows (they define the "
                "quantile grid and the perturbation distribution)",
                status_code=400,
                reason="BAD_REQUEST",
            )
        if self.background.shape[1] != m:
            raise MicroserviceError(
                f"background has {self.background.shape[1]} features, request has {m}",
                status_code=400,
                reason="BAD_REQUEST",
            )
        qs = np.linspace(0, 1, self.n_bins + 1)[1:-1]
        return np.quantile(self.background, qs, axis=0).T  # (m, n_bins-1)

    def _bins_of(self, rows: np.ndarray) -> np.ndarray:
        """Bin index per (row, feature) against the fitted edges."""
        out = np.zeros(rows.shape, np.int64)
        for j in range(rows.shape[1]):
            out[:, j] = np.searchsorted(self._edges[j], rows[:, j], side="right")
        return out

    def _predicate_str(self, j: int, b: int, names: List[str]) -> str:
        name = names[j] if j < len(names) else f"f{j}"
        edges = self._edges[j]
        lo = None if b == 0 else edges[b - 1]
        hi = None if b >= len(edges) else edges[b]
        if lo is None:
            return f"{name} <= {hi:.6g}"
        if hi is None:
            return f"{name} > {lo:.6g}"
        return f"{lo:.6g} < {name} <= {hi:.6g}"

    # ---- search -----------------------------------------------------------

    @staticmethod
    def _labels(preds: np.ndarray) -> np.ndarray:
        """Model outputs -> decision labels.  Multi-column outputs are
        argmax'd; a SINGLE column is treated as a binary PROBABILITY in
        [0, 1] and thresholded at 0.5 (e.g. the xgboost
        binary:logistic fallback returns (N,) probabilities).  Without
        this, a 1-wide output argmaxes to class 0 for every row and
        EVERY rule reads precision 1.0 — an arbitrary anchor reported
        as a perfect explanation.  NOTE: a raw-MARGIN single column
        (decision boundary 0, not 0.5) must be wrapped to probabilities
        (or two columns) before anchoring — the explainer cannot guess
        an arbitrary score's boundary."""
        p = np.asarray(preds)
        if p.ndim == 1:
            p = p[:, None]
        if p.shape[1] == 1:
            return (p[:, 0] > 0.5).astype(np.int64)
        return np.argmax(p, axis=1)

    def _perturb(
        self, x: np.ndarray, anchor: tuple, x_bins: np.ndarray,
        bg_bins: np.ndarray, rng: np.random.Generator,
    ) -> np.ndarray:
        """``n_samples`` background rows with anchored features redrawn
        from x's bin (falling back to x's own value when the background
        has no row in that bin — the predicate still holds)."""
        bg = self.background
        idx = rng.integers(0, len(bg), size=self.n_samples)
        Z = bg[idx].copy()
        for j in anchor:
            pool = bg[bg_bins[:, j] == x_bins[j], j]
            if len(pool):
                Z[:, j] = rng.choice(pool, size=self.n_samples, replace=True)
            else:
                Z[:, j] = x[j]
        return Z

    def _explain_row(
        self, x: np.ndarray, names: List[str], bg_bins: np.ndarray,
        rng: np.random.Generator,
    ) -> Dict[str, Any]:
        m = len(x)
        x_bins = self._bins_of(x[None])[0]
        target = int(self._labels(
            np.asarray(self.model.predict(x[None], names)).reshape(1, -1)
        )[0])
        max_size = min(self.max_anchor_size or m, m)

        def coverage(anchor: tuple) -> float:
            sat = np.ones(len(bg_bins), bool)
            for j in anchor:
                sat &= bg_bins[:, j] == x_bins[j]
            return float(sat.mean())

        beam: List[tuple] = [()]
        best: Dict[str, Any] = {"anchor": (), "precision": 0.0, "coverage": 1.0}
        seen: set = set()
        for _size in range(1, max_size + 1):
            # candidates: every beam rule extended by one unused feature
            cands = []
            for a in beam:
                for j in range(m):
                    if j in a:
                        continue
                    c = tuple(sorted(a + (j,)))
                    if c not in seen:
                        seen.add(c)
                        cands.append(c)
            if not cands:
                break
            # ONE batched predict for the whole round: every candidate's
            # n_samples perturbation rows, stacked
            Zs = [
                self._perturb(x, c, x_bins, bg_bins, rng) for c in cands
            ]
            batch = np.concatenate(Zs, axis=0)
            labels = self._labels(np.asarray(self.model.predict(batch, names)))
            precisions = [
                float((labels[i * self.n_samples:(i + 1) * self.n_samples] == target).mean())
                for i in range(len(cands))
            ]
            # rank by precision, ties by coverage (broader rules win)
            order = sorted(
                range(len(cands)),
                key=lambda i: (-precisions[i], -coverage(cands[i])),
            )
            top = order[0]
            if precisions[top] > best["precision"] or (
                precisions[top] == best["precision"] and not best["anchor"]
            ):
                best = {
                    "anchor": cands[top],
                    "precision": precisions[top],
                    "coverage": coverage(cands[top]),
                }
            if precisions[top] >= self.precision_threshold:
                break
            beam = [cands[i] for i in order[: self.beam_size]]
        anchor = best["anchor"]
        return {
            "features": list(anchor),
            "predicates": [
                self._predicate_str(j, int(x_bins[j]), names) for j in anchor
            ],
            "precision": best["precision"],
            "coverage": best["coverage"],
            "met_threshold": best["precision"] >= self.precision_threshold,
            "target": target,
        }

    def explain(self, X, names=None) -> Dict[str, Any]:
        if self.model is None:
            raise MicroserviceError(
                "AnchorsExplainer needs a model", status_code=400, reason="NO_MODEL"
            )
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        names = list(names or [])
        if self._edges is None:
            self._edges = self._fit_edges(X.shape[1])
        elif X.shape[1] != self._edges.shape[0]:
            # the grid is fitted to the background's width; a
            # later request with a different width is the client's
            # error (400), not an IndexError deep in _bins_of
            raise MicroserviceError(
                f"request has {X.shape[1]} features, explainer is fitted "
                f"for {self._edges.shape[0]}",
                status_code=400,
                reason="BAD_REQUEST",
            )
        bg_bins = self._bins_of(self.background)
        rng = np.random.default_rng(self.seed)
        rows = [self._explain_row(x, names, bg_bins, rng) for x in X]
        return {
            "method": "anchors",
            "anchors": rows,
            "targets": [r["target"] for r in rows],
            "precision_threshold": self.precision_threshold,
            "names": names,
        }

    # deployable as a MODEL node: rows of 0/1 anchor membership
    def predict(self, X, names, meta=None):
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        result = self.explain(X, names)
        out = np.zeros((len(X), X.shape[1]))
        for i, a in enumerate(result["anchors"]):
            out[i, a["features"]] = 1.0
        return out


EXPLAINER_TYPES: Dict[str, Callable[..., Any]] = {
    "integrated_gradients": IntegratedGradientsExplainer,
    "permutation": PermutationExplainer,
    "kernel_shap": KernelShapExplainer,
    "anchors": AnchorsExplainer,
}


def build_explainer(config: Dict[str, Any]) -> Any:
    etype = config.get("type", "integrated_gradients")
    factory = EXPLAINER_TYPES.get(etype)
    if factory is None:
        raise MicroserviceError(f"unknown explainer type {etype!r}", status_code=400, reason="UNKNOWN_EXPLAINER")
    params = {k: v for k, v in config.items() if k != "type"}
    return factory(**params)
