"""Learned routers — multi-armed bandits over graph branches.

TPU-native re-design of the reference's MAB components
(reference: components/routers/epsilon-greedy/EpsilonGreedy.py:9-150,
components/routers/thompson-sampling/ThompsonSampling.py): stateful
``route()`` + ``send_feedback()`` learning the best child branch online
from the reward signal the engine propagates back along the served
branch (reference call stack: SURVEY §3.3).

State is an explicit small array tree (counts / reward sums / Beta
posteriors) checkpointed through the persistence subsystem — not a
pickled object (reference: persistence.py) — so restores survive code
upgrades and the state can be inspected.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.runtime.component import TPUComponent, gauge_metric


class EpsilonGreedy(TPUComponent):
    """Explore with probability epsilon, else exploit the best branch.

    Reward model: running mean reward per branch (the reference models
    Bernoulli success/failure counts; a running mean generalises to
    real-valued rewards).
    """

    def __init__(
        self,
        n_branches: int = 2,
        epsilon: float = 0.1,
        decay: float = 1.0,
        seed: Optional[int] = None,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        if n_branches < 1:
            raise ValueError("n_branches must be >= 1")
        self.n_branches = int(n_branches)
        self.epsilon = float(epsilon)
        self.decay = float(decay)  # epsilon *= decay on every feedback
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self.counts = np.zeros(self.n_branches, dtype=np.int64)
        self.reward_sums = np.zeros(self.n_branches, dtype=np.float64)

    def branch_values(self) -> np.ndarray:
        with self._lock:
            return np.where(self.counts > 0, self.reward_sums / np.maximum(self.counts, 1), 0.0)

    def route(self, features, names) -> int:
        with self._lock:
            if self._rng.random() < self.epsilon:
                branch = int(self._rng.integers(self.n_branches))
            else:
                values = np.where(
                    self.counts > 0, self.reward_sums / np.maximum(self.counts, 1), np.inf
                )  # optimistic: try unexplored branches first
                branch = int(np.argmax(values))
        return branch

    def send_feedback(self, features, names, reward, truth, routing=None):
        if routing is None or not (0 <= routing < self.n_branches):
            return None
        with self._lock:
            self.counts[routing] += 1
            self.reward_sums[routing] += float(reward)
            self.epsilon *= self.decay
        return None

    def metrics(self) -> List[Dict]:
        values = self.branch_values()
        out = [gauge_metric("mab_epsilon", self.epsilon)]
        for i, v in enumerate(values):
            out.append(gauge_metric("mab_branch_value", float(v), tags={"branch": str(i)}))
        return out

    def checkpoint_state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counts": self.counts.copy(),
                "reward_sums": self.reward_sums.copy(),
                "epsilon": self.epsilon,
            }

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.counts = np.asarray(state["counts"], dtype=np.int64)
            self.reward_sums = np.asarray(state["reward_sums"], dtype=np.float64)
            self.epsilon = float(state.get("epsilon", self.epsilon))


class ThompsonSampling(TPUComponent):
    """Beta-Bernoulli posterior sampling per branch.

    Rewards are interpreted as success probabilities in [0, 1]
    (clipped); each feedback adds reward to alpha and (1 - reward) to
    beta, and routing samples each branch's posterior.
    """

    def __init__(self, n_branches: int = 2, seed: Optional[int] = None, **kwargs: Any):
        super().__init__(**kwargs)
        if n_branches < 1:
            raise ValueError("n_branches must be >= 1")
        self.n_branches = int(n_branches)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self.alpha = np.ones(self.n_branches, dtype=np.float64)
        self.beta = np.ones(self.n_branches, dtype=np.float64)

    def route(self, features, names) -> int:
        with self._lock:
            samples = self._rng.beta(self.alpha, self.beta)
        return int(np.argmax(samples))

    def send_feedback(self, features, names, reward, truth, routing=None):
        if routing is None or not (0 <= routing < self.n_branches):
            return None
        r = float(np.clip(reward, 0.0, 1.0))
        with self._lock:
            self.alpha[routing] += r
            self.beta[routing] += 1.0 - r
        return None

    def metrics(self) -> List[Dict]:
        with self._lock:
            means = self.alpha / (self.alpha + self.beta)
        return [
            gauge_metric("mab_branch_posterior_mean", float(m), tags={"branch": str(i)})
            for i, m in enumerate(means)
        ]

    def checkpoint_state(self) -> Dict[str, Any]:
        with self._lock:
            return {"alpha": self.alpha.copy(), "beta": self.beta.copy()}

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.alpha = np.asarray(state["alpha"], dtype=np.float64)
            self.beta = np.asarray(state["beta"], dtype=np.float64)
