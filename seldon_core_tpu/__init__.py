"""seldon-core-tpu: a TPU-native model-serving framework.

A ground-up re-design of the Seldon Core serving platform (reference
snapshot under /root/reference) for TPU hardware:

* the wire contract (``SeldonMessage``) is kept compatible, with an added
  zero-copy ``RawTensor`` payload that maps straight into device buffers;
* the inference-graph orchestrator (the reference's Java "engine") is an
  in-process async executor — co-located graph edges hand off
  device-resident ``jax.Array``s instead of re-serialising JSON per hop;
* models are jit-compiled to XLA with weights pinned in HBM, served
  through a dynamic batcher, and optionally pjit-sharded over an ICI mesh;
* the control plane places graph nodes onto TPU devices instead of
  Kubernetes pods.
"""

__version__ = "0.1.0"
