"""Typed per-node parameter parsing.

The control plane passes each graph node a list of typed parameters
(name/value/type) which become constructor kwargs for the user class —
the same contract as the reference's ``PREDICTIVE_UNIT_PARAMETERS`` env
var (reference: python/seldon_core/microservice.py:50-96) and the
engine-side mirror (reference: PredictiveUnitState.java:100-113).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

PARAMETERS_ENV_NAME = "PREDICTIVE_UNIT_PARAMETERS"
SERVICE_PORT_ENV_NAME = "PREDICTIVE_UNIT_SERVICE_PORT"
UNIT_ID_ENV_NAME = "PREDICTIVE_UNIT_ID"

_TYPE_PARSERS = {
    "STRING": str,
    "INT": int,
    "FLOAT": float,
    "DOUBLE": float,
    "BOOL": lambda v: str(v).lower() in ("1", "true", "yes"),
    "JSON": lambda v: json.loads(v) if isinstance(v, str) else v,
}


class ParameterError(ValueError):
    pass


def parse_parameters(parameters: List[Dict[str, Any]]) -> Dict[str, Any]:
    """[{"name": n, "value": v, "type": t}, ...] -> constructor kwargs."""
    kwargs: Dict[str, Any] = {}
    for p in parameters or []:
        if "name" not in p:
            raise ParameterError(f"parameter missing 'name': {p!r}")
        ptype = str(p.get("type", "STRING")).upper()
        parser = _TYPE_PARSERS.get(ptype)
        if parser is None:
            raise ParameterError(f"unknown parameter type {ptype!r} for {p['name']!r}")
        try:
            kwargs[p["name"]] = parser(p.get("value"))
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            raise ParameterError(f"cannot parse parameter {p['name']!r}: {e}") from e
    return kwargs


def parameters_from_env(environ: Dict[str, str] = None) -> Dict[str, Any]:
    environ = environ if environ is not None else os.environ
    raw = environ.get(PARAMETERS_ENV_NAME, "[]")
    try:
        parsed = json.loads(raw)
    except json.JSONDecodeError as e:
        raise ParameterError(f"{PARAMETERS_ENV_NAME} is not valid JSON: {e}") from e
    return parse_parameters(parsed)
