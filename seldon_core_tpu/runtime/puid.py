"""Process-unique request ids (puids).

The puid is the correlation key of the whole observability stack — the
logical trace id, the pair-log key, the feedback router's lookup.  Two
hazards make the obvious ``prefix + itertools.count()`` unsafe:

* a respawned worker restarts its counter at 0, so two process
  *generations* of one replica mint colliding puids and their traces /
  logged pairs merge silently;
* a process that **forks** after import (supervisor pre-fork, test
  harnesses) duplicates both the prefix and the live counter state into
  every child.

The generator therefore re-seeds its random prefix whenever it notices
it is running in a new process (pid change), and the prefix comes from
``secrets`` per process generation — collision probability across any
realistic fleet of generations is 2^-48 per pair.  The counter gives
uniqueness and cheapness (no entropy syscall per request — urandom
showed up in the serving-path profile) within a generation.
"""

from __future__ import annotations

import itertools
import os
import secrets
import threading

_lock = threading.Lock()
_seeded = False
_prefix = ""
_counter = itertools.count()


def _reseed() -> None:
    global _seeded, _prefix, _counter
    with _lock:
        if _seeded:
            return  # another thread won the race — ONE generation only
        _prefix = secrets.token_hex(6)
        _counter = itertools.count()
        _seeded = True


def _invalidate() -> None:  # runs in the child right after a fork
    global _seeded
    _seeded = False


# fork invalidation via the interpreter hook rather than a per-call
# getpid(): the syscall on the minting path is exactly what the prefix+
# counter design exists to avoid (fresh processes re-import and reseed
# on first use either way)
os.register_at_fork(after_in_child=_invalidate)


def new_puid() -> str:
    """Unique request id (reference: PredictionService.java:72-78),
    collision-safe across processes, respawns, and forks."""
    if not _seeded:
        _reseed()
    # one consistent (prefix, counter) snapshot: the only writer after
    # seeding is the fork hook, and a freshly-forked child is
    # single-threaded, so a generation's pair can't be torn here
    prefix, counter = _prefix, _counter
    return f"{prefix}{next(counter):012x}"
