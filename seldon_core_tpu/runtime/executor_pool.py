"""Shared dispatch thread pool.

``asyncio.to_thread`` uses the loop's default executor, sized
``min(32, cpu_count + 4)`` — on a 1-CPU serving host that is 5 threads,
and since a component call *blocks* its thread while waiting on the
dynamic batcher, the default pool caps in-flight requests (measured:
it flatlined the ResNet-50 benchmark at ~80 QPS).  Dispatch threads
spend their life blocked on futures or inside GIL-releasing XLA calls,
so a much larger pool costs little and restores concurrency.
"""

from __future__ import annotations

import asyncio
import contextvars
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

_POOL: ThreadPoolExecutor | None = None


def dispatch_pool() -> ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        from seldon_core_tpu.runtime import knobs

        workers = int(knobs.raw("SELDON_TPU_DISPATCH_THREADS", "128"))
        _POOL = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="seldon-dispatch")
    return _POOL


async def run_dispatch(fn: Callable, *args: Any):
    """Run a sync dispatch call on the shared pool.

    The caller's contextvars (in particular the active tracing span /
    an extracted remote span context) are copied onto the pool thread —
    ``run_in_executor`` alone would drop them, making every dispatch
    span a fresh root (asyncio.to_thread does the same copy)."""
    loop = asyncio.get_running_loop()
    ctx = contextvars.copy_context()
    return await loop.run_in_executor(dispatch_pool(), lambda: ctx.run(fn, *args))
