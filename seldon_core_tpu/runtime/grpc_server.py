"""gRPC server for a single graph-node microservice.

Registers all seven node-role services against one user component, the
same all-servicers-on-one-object pattern as the reference wrapper
(reference: python/seldon_core/wrapper.py:133-158), using gRPC generic
handlers (no generated stubs).  Uses the async server; user-model calls
run on worker threads so device compute overlaps request handling.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Optional

import grpc

from seldon_core_tpu.proto import pb, services
from seldon_core_tpu.runtime import dispatch
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage

logger = logging.getLogger(__name__)

DEFAULT_MAX_MSG_BYTES = 512 * 1024 * 1024


def _grpc_remote_ctx(context):
    """The caller's W3C span context from the invocation metadata
    (GrpcClient injects traceparent/tracestate there — the proto has
    no meta field for it)."""
    from seldon_core_tpu.utils.tracing import extract, get_tracer

    if get_tracer() is None:
        return None
    try:
        return extract(context.invocation_metadata() or ())
    except Exception:  # noqa: BLE001 — bad metadata must not fail the call
        return None


def _grpc_deadline_ms(context):
    """The caller's remaining budget: the tighter of the
    ``x-seldon-deadline-ms`` metadata entry and the native gRPC
    deadline (``context.time_remaining()``), in milliseconds; None when
    neither is set."""
    from seldon_core_tpu.utils import deadlines

    md_ms = None
    try:
        md_ms = deadlines.extract_ms(context.invocation_metadata() or ())
    except Exception:  # noqa: BLE001 — bad metadata must not fail the call
        md_ms = None
    native_ms = None
    try:
        remaining = context.time_remaining()
        if remaining is not None:
            native_ms = max(0.0, float(remaining) * 1000.0)
    except Exception:  # noqa: BLE001 — bad metadata must not fail the call
        native_ms = None
    if md_ms is None:
        return native_ms
    if native_ms is None:
        return md_ms
    return min(md_ms, native_ms)


def _wrap_unary(user_model: Any, fn, unit_id: str = ""):
    async def handler(request, context):
        from seldon_core_tpu.runtime.executor_pool import run_dispatch
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            with activate_context(_grpc_remote_ctx(context)), \
                    _deadlines.activate_ms(_grpc_deadline_ms(context)):
                _deadlines.check(f"microservice grpc ingress {fn.__name__}")
                if isinstance(request, pb.Feedback):
                    arg = InternalFeedback.from_proto(request)
                    out = await run_dispatch(fn, user_model, arg, unit_id)
                elif isinstance(request, pb.SeldonMessageList):
                    msgs = [InternalMessage.from_proto(m) for m in request.seldonMessages]
                    out = await run_dispatch(fn, user_model, msgs)
                else:
                    msg = InternalMessage.from_proto(request)
                    # x-seldon-adapter metadata selects the LoRA weight
                    # set (r16), REST-lane parity: body tag wins
                    adapter = _deadlines.extract_adapter(
                        context.invocation_metadata() or ()
                    )
                    if adapter and "adapter" not in msg.meta.tags:
                        msg.meta.tags["adapter"] = adapter
                    if fn is dispatch.predict:  # async fast path for batched models
                        out = await dispatch.predict_async(user_model, msg)
                    else:
                        out = await run_dispatch(fn, user_model, msg)
            return out.to_proto()
        except MicroserviceError as e:
            resp = pb.SeldonMessage()
            resp.status.status = pb.Status.FAILURE
            resp.status.code = e.status_code
            resp.status.info = e.message
            resp.status.reason = e.reason
            return resp
        except Exception as e:  # noqa: BLE001
            logger.exception("grpc handler error")
            resp = pb.SeldonMessage()
            resp.status.status = pb.Status.FAILURE
            resp.status.code = 500
            resp.status.info = str(e)
            resp.status.reason = "MICROSERVICE_INTERNAL_ERROR"
            return resp

    return handler


def add_component_services(server: grpc.aio.Server, user_model: Any, unit_id: str = "") -> None:
    """Register Generic/Model/Router/Transformer/OutputTransformer/
    Combiner for `user_model` on `server`."""
    p = _wrap_unary(user_model, dispatch.predict)
    ti = _wrap_unary(user_model, dispatch.transform_input)
    to = _wrap_unary(user_model, dispatch.transform_output)
    rt = _wrap_unary(user_model, dispatch.route)
    ag = _wrap_unary(user_model, dispatch.aggregate)
    fb = _wrap_unary(user_model, dispatch.send_feedback, unit_id)

    server.add_generic_rpc_handlers(
        (
            services.generic_handler(
                "Generic",
                {"TransformInput": ti, "TransformOutput": to, "Route": rt, "Aggregate": ag, "SendFeedback": fb},
            ),
            services.generic_handler("Model", {"Predict": p, "SendFeedback": fb}),
            services.generic_handler("Router", {"Route": rt, "SendFeedback": fb}),
            services.generic_handler("Transformer", {"TransformInput": ti}),
            services.generic_handler("OutputTransformer", {"TransformOutput": to}),
            services.generic_handler("Combiner", {"Aggregate": ag}),
        )
    )


def build_server(
    user_model: Any,
    unit_id: str = "",
    max_message_bytes: int = DEFAULT_MAX_MSG_BYTES,
) -> grpc.aio.Server:
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", max_message_bytes),
            ("grpc.max_receive_message_length", max_message_bytes),
        ]
    )
    add_component_services(server, user_model, unit_id)
    return server


async def serve(
    user_model: Any,
    port: int = 5000,
    host: str = "0.0.0.0",
    unit_id: str = "",
    max_message_bytes: int = DEFAULT_MAX_MSG_BYTES,
    tls=None,
) -> grpc.aio.Server:
    from seldon_core_tpu.utils.tls import add_grpc_port

    server = build_server(user_model, unit_id, max_message_bytes)
    add_grpc_port(server, f"{host}:{port}", tls)
    await server.start()
    return server
