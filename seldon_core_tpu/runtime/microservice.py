"""Microservice CLI — wrap one user component as a serving process.

Equivalent of the reference's ``seldon-core-microservice`` entrypoint
(reference: python/seldon_core/microservice.py:186-375):

    seldon-tpu-microservice mypkg.MyModel --api BOTH --http-port 9000 \
        --grpc-port 5000 --service-type MODEL \
        --parameters '[{"name":"n","value":"2","type":"FLOAT"}]'

Differences from the reference, by design:

* one process serves REST **and** gRPC concurrently on one asyncio loop
  (the reference forces a choice of one transport per container);
* scale-out is replica processes managed by the control plane rather
  than gunicorn forks — TPU devices can't be shared by forked workers;
* component state restore/persist uses the checkpoint subsystem instead
  of whole-object pickling to Redis.
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import json
import logging
import os
import signal
import sys
from typing import Any, Dict, List, Optional

from seldon_core_tpu.runtime import knobs

from seldon_core_tpu.runtime.params import (
    PARAMETERS_ENV_NAME,
    SERVICE_PORT_ENV_NAME,
    UNIT_ID_ENV_NAME,
    parse_parameters,
)

logger = logging.getLogger(__name__)

SERVICE_TYPES = (
    "MODEL",
    "ROUTER",
    "TRANSFORMER",
    "OUTPUT_TRANSFORMER",
    "COMBINER",
    "OUTLIER_DETECTOR",
)


def import_component(dotted: str, **kwargs: Any) -> Any:
    """Instantiate a component with typed parameter kwargs.

    Accepts ``pkg.module.Class`` or the reference s2i contract's bare
    name ``MyModel`` — module ``MyModel`` defining ``class MyModel``
    (reference: python/seldon_core/microservice.py interface_name).
    """
    module_name, _, class_name = dotted.rpartition(".")
    if not module_name:
        module_name = class_name = dotted
    sys.path.insert(0, os.getcwd())
    module = importlib.import_module(module_name)
    cls = getattr(module, class_name)
    return cls(**kwargs)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="seldon-core-tpu microservice")
    parser.add_argument("component", help="dotted path module.Class of the user component")
    parser.add_argument("--api", choices=("REST", "GRPC", "BOTH"), default="BOTH")
    parser.add_argument("--service-type", choices=SERVICE_TYPES, default="MODEL")
    parser.add_argument(
        "--http-port",
        type=int,
        default=int(os.environ.get(SERVICE_PORT_ENV_NAME, 9000)),
    )
    parser.add_argument("--grpc-port", type=int, default=5000)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument(
        "--parameters", default=os.environ.get(PARAMETERS_ENV_NAME, "[]"),
        help="typed parameter list JSON",
    )
    parser.add_argument("--unit-id", default=os.environ.get(UNIT_ID_ENV_NAME, ""))
    parser.add_argument("--persistence", action="store_true", help="periodically checkpoint component state")
    parser.add_argument("--persistence-dir", default=os.environ.get("PERSISTENCE_DIR", "/tmp/seldon-tpu-state"))
    parser.add_argument("--persistence-period-s", type=float, default=60.0)
    parser.add_argument("--ssl-cert", default=os.environ.get("SELDON_TLS_CERT", ""),
                        help="PEM certificate; enables TLS on REST and gRPC")
    parser.add_argument("--ssl-key", default=os.environ.get("SELDON_TLS_KEY", ""))
    parser.add_argument("--ssl-ca", default=os.environ.get("SELDON_TLS_CA", ""),
                        help="peer-verification CA (with --ssl-require-client-auth: mTLS)")
    parser.add_argument("--ssl-require-client-auth", action="store_true",
                        default=os.environ.get("SELDON_TLS_REQUIRE_CLIENT_AUTH", "0") == "1")
    parser.add_argument("--tracing", action="store_true", default=bool(int(os.environ.get("TRACING", "0"))))
    parser.add_argument("--log-level", default=os.environ.get("SELDON_LOG_LEVEL", "INFO"))
    parser.add_argument(
        "--platform", default=knobs.raw("SELDON_TPU_PLATFORM", ""),
        help="force the jax platform (cpu|tpu|...). Needed because some "
        "environments pre-import jax before env vars like JAX_PLATFORMS "
        "can take effect; applied through jax.config before backend init",
    )
    return parser.parse_args(argv)


async def run_servers(
    user_model: Any,
    api: str = "BOTH",
    host: str = "0.0.0.0",
    http_port: int = 9000,
    grpc_port: int = 5000,
    unit_id: str = "",
    shutdown_event: Optional[asyncio.Event] = None,
    tls=None,
) -> None:
    """Serve until `shutdown_event` (or forever)."""
    from seldon_core_tpu.runtime import grpc_server, rest

    runner = None
    server = None
    secure = " (TLS)" if tls is not None and tls.enabled else ""
    if api in ("REST", "BOTH"):
        app = rest.build_app(user_model, unit_id=unit_id)
        runner = await rest.serve(app, host=host, port=http_port, tls=tls)
        logger.info("REST serving on %s:%d%s", host, http_port, secure)
    if api in ("GRPC", "BOTH"):
        server = await grpc_server.serve(
            user_model, port=grpc_port, host=host, unit_id=unit_id, tls=tls
        )
        logger.info("gRPC serving on %s:%d%s", host, grpc_port, secure)

    if shutdown_event is None:
        shutdown_event = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, shutdown_event.set)
            except NotImplementedError:  # pragma: no cover
                pass
    await shutdown_event.wait()

    # drain-then-exit (r12): components exposing drain() — StreamingLM's
    # generation engine — journal their live streams FIRST, so in-flight
    # handlers unblock with a clean 503 DRAINING immediately (instead of
    # hanging into the gRPC grace window) and the respawned worker
    # replays the journal (SELDON_TPU_DRAIN_JOURNAL, pinned per worker
    # by the supervisor) through the ordinary submit path.
    drain_fn = getattr(user_model, "drain", None)
    if callable(drain_fn):
        try:
            await asyncio.get_running_loop().run_in_executor(None, drain_fn)
        except Exception:  # noqa: BLE001 — drain is best-effort; exit anyway
            logger.exception("component drain failed during shutdown")

    if server is not None:
        await server.stop(grace=20.0)
    if runner is not None:
        await runner.cleanup()


def start_custom_service(user_model: Any):
    """Run the component's optional ``custom_service()`` side loop on a
    daemon thread (the reference runs it as a second process,
    reference: microservice.py:29-47,363-368 — a thread gives the same
    lifetime without the fork). Returns the thread, or None."""
    if not hasattr(user_model, "custom_service"):
        return None
    import threading

    thread = threading.Thread(
        target=user_model.custom_service, name="custom-service", daemon=True
    )
    thread.start()
    return thread


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_args(argv)
    logging.basicConfig(level=args.log_level.upper(), format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.unit_id:
        # export the unit identity for in-process consumers that have
        # no CLI access (the telemetry ring's replica_id): supervised
        # workers get --unit-id on argv, not in their environment
        os.environ.setdefault(UNIT_ID_ENV_NAME, args.unit_id)

    kwargs = parse_parameters(json.loads(args.parameters))
    user_model = import_component(args.component, **kwargs)

    if args.tracing:
        from seldon_core_tpu.utils.tracing import setup_tracing

        # SELDON_TPU_TRACE_EXPORT: JSONL span sink for this process —
        # the per-process artifact tools/profile_trace_stitch.py reads
        # to reassemble one cross-process trace (OTLP export rides the
        # standard OTEL_EXPORTER_OTLP_ENDPOINT env either way)
        setup_tracing(
            service_name=args.unit_id or args.component,
            export_path=knobs.raw("SELDON_TPU_TRACE_EXPORT") or None,
        )

    persistence_thread = None
    if args.persistence:
        from seldon_core_tpu.utils.persistence import PersistenceManager

        manager = PersistenceManager(args.persistence_dir, args.unit_id or args.component)
        manager.restore(user_model)
        persistence_thread = manager.start_background(user_model, period_s=args.persistence_period_s)

    if hasattr(user_model, "load"):
        user_model.load()

    start_custom_service(user_model)

    tls = None
    if args.ssl_cert or args.ssl_key:
        # key-without-cert must fail loudly (TlsConfig raises), not
        # silently serve the plaintext the operator thinks is TLS
        from seldon_core_tpu.utils.tls import TlsConfig

        tls = TlsConfig(
            cert_file=args.ssl_cert,
            key_file=args.ssl_key,
            ca_file=args.ssl_ca,
            require_client_auth=args.ssl_require_client_auth,
        )

    try:
        asyncio.run(
            run_servers(
                user_model,
                api=args.api,
                host=args.host,
                http_port=args.http_port,
                grpc_port=args.grpc_port,
                unit_id=args.unit_id,
                tls=tls,
            )
        )
    finally:
        if persistence_thread is not None:
            persistence_thread.stop()


if __name__ == "__main__":
    main()
