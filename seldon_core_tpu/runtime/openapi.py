"""OpenAPI document generation for the REST surfaces.

The reference ships hand-maintained OAS3 JSON for the engine and
wrapper APIs, served by the wrapper at ``/seldon.json``
(reference: openapi/engine.oas3.json, openapi/wrapper.oas3.json,
python/seldon_core/wrapper.py:36-38).  Here the documents are generated
from one schema source so they can't drift from the code.
"""

from __future__ import annotations

from typing import Any, Dict

from seldon_core_tpu import __version__

_SELDON_MESSAGE_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "properties": {
        "status": {"$ref": "#/components/schemas/Status"},
        "meta": {"$ref": "#/components/schemas/Meta"},
        "data": {"$ref": "#/components/schemas/DefaultData"},
        "binData": {"type": "string", "format": "byte"},
        "strData": {"type": "string"},
        "jsonData": {},
    },
}

_SCHEMAS: Dict[str, Any] = {
    "SeldonMessage": _SELDON_MESSAGE_SCHEMA,
    "SeldonMessageList": {
        "type": "object",
        "properties": {
            "seldonMessages": {
                "type": "array",
                "items": {"$ref": "#/components/schemas/SeldonMessage"},
            }
        },
    },
    "DefaultData": {
        "type": "object",
        "properties": {
            "names": {"type": "array", "items": {"type": "string"}},
            "tensor": {"$ref": "#/components/schemas/Tensor"},
            "ndarray": {"type": "array", "items": {}},
            "rawTensor": {"$ref": "#/components/schemas/RawTensor"},
        },
    },
    "Tensor": {
        "type": "object",
        "properties": {
            "shape": {"type": "array", "items": {"type": "integer"}},
            "values": {"type": "array", "items": {"type": "number"}},
        },
    },
    "RawTensor": {
        "type": "object",
        "description": "zero-copy typed tensor: base64 little-endian bytes",
        "properties": {
            "shape": {"type": "array", "items": {"type": "integer"}},
            "dtype": {"type": "string", "example": "float32"},
            "data": {"type": "string", "format": "byte"},
        },
    },
    "Meta": {
        "type": "object",
        "properties": {
            "puid": {"type": "string"},
            "tags": {"type": "object"},
            "routing": {"type": "object", "additionalProperties": {"type": "integer"}},
            "requestPath": {"type": "object", "additionalProperties": {"type": "string"}},
            "metrics": {"type": "array", "items": {"$ref": "#/components/schemas/Metric"}},
        },
    },
    "Metric": {
        "type": "object",
        "properties": {
            "key": {"type": "string"},
            "type": {"type": "string", "enum": ["COUNTER", "GAUGE", "TIMER"]},
            "value": {"type": "number"},
            "tags": {"type": "object", "additionalProperties": {"type": "string"}},
        },
    },
    "Status": {
        "type": "object",
        "properties": {
            "code": {"type": "integer"},
            "info": {"type": "string"},
            "reason": {"type": "string"},
            "status": {"type": "string", "enum": ["SUCCESS", "FAILURE"]},
        },
    },
    "Feedback": {
        "type": "object",
        "properties": {
            "request": {"$ref": "#/components/schemas/SeldonMessage"},
            "response": {"$ref": "#/components/schemas/SeldonMessage"},
            "reward": {"type": "number"},
            "truth": {"$ref": "#/components/schemas/SeldonMessage"},
        },
    },
}


def _message_op(summary: str, request_schema: str = "SeldonMessage") -> Dict[str, Any]:
    return {
        "post": {
            "summary": summary,
            "requestBody": {
                "content": {
                    "application/json": {
                        "schema": {"$ref": f"#/components/schemas/{request_schema}"}
                    }
                },
                "required": True,
            },
            "responses": {
                "200": {
                    "description": "response message",
                    "content": {
                        "application/json": {
                            "schema": {"$ref": "#/components/schemas/SeldonMessage"}
                        }
                    },
                }
            },
        }
    }


def wrapper_openapi() -> Dict[str, Any]:
    """The node-microservice REST API (reference: wrapper.oas3.json)."""
    return {
        "openapi": "3.0.0",
        "info": {"title": "seldon-core-tpu node microservice API", "version": __version__},
        "paths": {
            "/predict": _message_op("model prediction"),
            "/transform-input": _message_op("input transformation"),
            "/transform-output": _message_op("output transformation"),
            "/route": _message_op("routing decision"),
            "/aggregate": _message_op("combine child outputs", "SeldonMessageList"),
            "/send-feedback": _message_op("reward feedback", "Feedback"),
            "/health/ping": {"get": {"summary": "liveness", "responses": {"200": {"description": "pong"}}}},
            "/health/status": {"get": {"summary": "component health", "responses": {"200": {"description": "status"}}}},
            "/metrics": {"get": {"summary": "prometheus metrics", "responses": {"200": {"description": "text exposition"}}}},
        },
        "components": {"schemas": _SCHEMAS},
    }


def gateway_openapi() -> Dict[str, Any]:
    """The external deployment API (reference: engine.oas3.json)."""
    return {
        "openapi": "3.0.0",
        "info": {"title": "seldon-core-tpu deployment API", "version": __version__},
        "paths": {
            "/api/v0.1/predictions": _message_op("graph prediction"),
            "/api/v0.1/feedback": _message_op("reward feedback", "Feedback"),
            "/api/v0.1/explanations": _message_op("model explanation"),
            "/ping": {"get": {"summary": "liveness", "responses": {"200": {"description": "pong"}}}},
            "/ready": {"get": {"summary": "graph readiness", "responses": {"200": {"description": "ready"}, "503": {"description": "not ready"}}}},
            "/metrics": {"get": {"summary": "prometheus metrics", "responses": {"200": {"description": "text exposition"}}}},
        },
        "components": {"schemas": _SCHEMAS},
    }
