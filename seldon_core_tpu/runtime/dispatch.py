"""Node-method dispatch: InternalMessage -> user component -> InternalMessage.

The wrapper-side execution semantics of the reference
(reference: python/seldon_core/seldon_methods.py:28-344):

1. if the component defines a proto-level ``<method>_raw`` override, use
   it (converting to/from proto at this one point);
2. otherwise decode features, call the array-level user method, and wrap
   the result echoing the request's wire encoding, attaching
   ``class_names``/``tags``/``metrics``.

Unlike the reference there is a single code path — ``InternalMessage``
— rather than parallel proto and JSON implementations; boundary servers
convert once.  The payload handed to user code may be a device-resident
``jax.Array`` when the producer kept it on device and the consumer opts
in (``accepts_device_arrays = True`` on the component); by default it is
materialised to numpy for reference-compatible semantics.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional

import numpy as np

from seldon_core_tpu import codec
from seldon_core_tpu.runtime import component as comp
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage

logger = logging.getLogger(__name__)


def _features_for(user_model: Any, msg: InternalMessage) -> Any:
    """The payload as the user method sees it."""
    if codec.is_device_array(msg.payload) and not getattr(user_model, "accepts_device_arrays", False):
        return msg.host_payload()
    return msg.payload


def _construct_response(
    user_model: Any, msg: InternalMessage, result: Any
) -> InternalMessage:
    """Wrap a user-method result (reference: utils.py:426-498)."""
    if isinstance(result, InternalMessage):
        result.meta.trace_context = {}
        return result
    out = msg.with_payload(result)
    # hop state never rides a response: the carrier was consumed at
    # dispatch, but a concurrent sibling hop may have re-injected into
    # the shared request meta this copy inherits
    out.meta.trace_context = {}
    if isinstance(result, (bytes, str, dict)):
        out.names = []
    else:
        names = comp.get_class_names(user_model)
        out.names = names if names else []
    # per-node meta contributions
    tags = comp.get_custom_tags(user_model)
    if tags:
        out.meta.tags.update(tags)
    metrics = comp.get_custom_metrics(user_model)
    out.meta.metrics = list(metrics) if metrics else []
    return out


def _try_raw(user_model: Any, raw_name: str, msg) -> Optional[InternalMessage]:
    """Proto-level override path (``predict_raw`` etc.)."""
    fn = getattr(user_model, raw_name, None)
    if fn is None:
        return None
    try:
        result = fn(msg.to_proto())
    except comp.NotImplementedByUser:
        return None
    return InternalMessage.from_proto(result)


def _ensure_puid(msg) -> str:
    """puid of the message (or its feedback request), assigning one when
    the caller didn't — standalone microservices have no engine upstream
    to mint ids, and tracing/logging need a non-empty trace id."""
    first = msg[0] if isinstance(msg, list) and msg else msg
    meta = getattr(first, "meta", None) or getattr(
        getattr(first, "request", None), "meta", None
    )
    if meta is None:
        return ""
    if not meta.puid:
        import uuid

        meta.puid = uuid.uuid4().hex[:24]
    return meta.puid


def _consume_trace_context(msg):
    """Pop the W3C trace-context carrier off the message meta (and, for
    lists, every member) so responses never echo the caller's context
    downstream, and parse it into a SpanContext (or None).

    Consumption happens even when tracing is off: the carrier is hop
    state, not payload."""
    first = msg
    if isinstance(msg, list):
        if not msg:
            return None
        first = msg[0]
        for other in msg[1:]:
            meta = getattr(other, "meta", None)
            if meta is not None:
                meta.trace_context = {}
    meta = getattr(first, "meta", None) or getattr(
        getattr(first, "request", None), "meta", None
    )
    if meta is None or not meta.trace_context:
        return None
    carrier, meta.trace_context = meta.trace_context, {}
    from seldon_core_tpu.utils.tracing import extract

    return extract(carrier)


def _traced(method_name: str):
    """Span per microservice method call — the wrapper-level tracing the
    reference does around its endpoints (microservice.py:124-155).
    No-op (one global read) when tracing is not set up.

    Cross-process parenting: a remote context extracted from the
    message meta (or already activated by the REST/gRPC server from
    headers/metadata) makes this span a CHILD of the caller's span —
    never a fresh root.  An ambient in-process span wins over the meta
    carrier (they agree when both exist; the ambient one carries more
    structure)."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(user_model, msg, *args, **kwargs):
            from seldon_core_tpu.utils import tracing

            puid = _ensure_puid(msg)
            ctx = _consume_trace_context(msg)
            if tracing.get_tracer() is None:
                return fn(user_model, msg, *args, **kwargs)
            if tracing.current_span() is not None:
                ctx = None
            with tracing.activate_context(ctx):
                with tracing.maybe_span(f"microservice.{method_name}", trace_id=puid):
                    return fn(user_model, msg, *args, **kwargs)

        return wrapper

    return deco


@_traced("predict")
def predict(user_model: Any, msg: InternalMessage) -> InternalMessage:
    raw = _try_raw(user_model, "predict_raw", msg)
    if raw is not None:
        return raw
    features = _features_for(user_model, msg)
    result = user_model.predict(features, msg.names, meta=msg.meta.to_dict())
    return _construct_response(user_model, msg, result)


async def predict_async(user_model: Any, msg: InternalMessage) -> InternalMessage:
    """Async-native predict: awaits a component's ``predict_async`` if it
    has one (e.g. JaxServer's batcher-backed path), else falls back to
    the sync dispatch on the shared pool."""
    fn = getattr(user_model, "predict_async", None)
    if fn is None or hasattr(user_model, "predict_raw"):
        from seldon_core_tpu.runtime.executor_pool import run_dispatch

        return await run_dispatch(predict, user_model, msg)
    from seldon_core_tpu.utils import tracing

    puid = _ensure_puid(msg)
    ctx = _consume_trace_context(msg)
    if tracing.current_span() is not None:
        ctx = None
    with tracing.activate_context(ctx if tracing.get_tracer() is not None else None):
        with tracing.maybe_span("microservice.predict", trace_id=puid):
            features = _features_for(user_model, msg)
            result = await fn(features, msg.names, meta=msg.meta.to_dict())
    return _construct_response(user_model, msg, result)


@_traced("transform_input")
def transform_input(user_model: Any, msg: InternalMessage) -> InternalMessage:
    raw = _try_raw(user_model, "transform_input_raw", msg)
    if raw is not None:
        return raw
    features = _features_for(user_model, msg)
    result = user_model.transform_input(features, msg.names, meta=msg.meta.to_dict())
    return _construct_response(user_model, msg, result)


@_traced("transform_output")
def transform_output(user_model: Any, msg: InternalMessage) -> InternalMessage:
    raw = _try_raw(user_model, "transform_output_raw", msg)
    if raw is not None:
        return raw
    features = _features_for(user_model, msg)
    result = user_model.transform_output(features, msg.names, meta=msg.meta.to_dict())
    return _construct_response(user_model, msg, result)


@_traced("route")
def route(user_model: Any, msg: InternalMessage) -> InternalMessage:
    """Returns a message whose payload is [[branch_index]]
    (reference: seldon_methods.py route semantics)."""
    fn = getattr(user_model, "route_raw", None)
    if fn is not None:
        try:
            return InternalMessage.from_proto(fn(msg.to_proto()))
        except comp.NotImplementedByUser:
            pass
    features = _features_for(user_model, msg)
    branch = user_model.route(features, msg.names)
    if not isinstance(branch, (int, np.integer)):
        raise comp.MicroserviceError(
            f"route must return int, got {type(branch).__name__}", status_code=500, reason="INVALID_ROUTING"
        )
    out = _construct_response(user_model, msg, np.array([[int(branch)]]))
    out.kind = "ndarray"
    return out


@_traced("aggregate")
def aggregate(user_model: Any, msgs: List[InternalMessage]) -> InternalMessage:
    fn = getattr(user_model, "aggregate_raw", None)
    if fn is not None:
        try:
            from seldon_core_tpu.proto import pb

            msg_list = pb.SeldonMessageList(seldonMessages=[m.to_proto() for m in msgs])
            return InternalMessage.from_proto(fn(msg_list))
        except comp.NotImplementedByUser:
            pass
    if not msgs:
        raise comp.MicroserviceError("aggregate called with no inputs", status_code=400, reason="EMPTY_AGGREGATE")
    features_list = [_features_for(user_model, m) for m in msgs]
    names_list = [m.names for m in msgs]
    result = user_model.aggregate(features_list, names_list)
    out = _construct_response(user_model, msgs[0], result)
    # meta of an aggregate response starts from the union of inputs
    for m in msgs[1:]:
        merged = dict(m.meta.tags)
        merged.update(out.meta.tags)
        out.meta.tags = merged
    return out


@_traced("send_feedback")
def send_feedback(
    user_model: Any, feedback: InternalFeedback, predictive_unit_id: Optional[str] = None
) -> InternalMessage:
    """Reference: seldon_methods.py:74-120 — routing picked from the
    response meta for this unit, default response is an empty array."""
    fn = getattr(user_model, "send_feedback_raw", None)
    if fn is not None:
        try:
            return InternalMessage.from_proto(fn(feedback.to_proto()))
        except comp.NotImplementedByUser:
            pass
    request = feedback.request
    features = _features_for(user_model, request) if request is not None else None
    names = request.names if request is not None else []
    truth = feedback.truth.host_payload() if feedback.truth is not None else None
    routing = None
    if feedback.response is not None and predictive_unit_id:
        routing = feedback.response.meta.routing.get(predictive_unit_id)
    result = None
    if hasattr(user_model, "send_feedback"):
        try:
            result = user_model.send_feedback(features, names, feedback.reward, truth, routing=routing)
        except comp.NotImplementedByUser:
            result = None
    if result is None:
        result = np.array([])
    base = request if request is not None else InternalMessage(kind="ndarray")
    return _construct_response(user_model, base, np.asarray(result))


def health_check(user_model: Any) -> InternalMessage:
    """Optional user health hook; defaults to a static OK payload."""
    fn = getattr(user_model, "health_status", None)
    if fn is not None:
        result = fn()
        return _construct_response(user_model, InternalMessage(kind="ndarray"), result)
    return InternalMessage(payload={"status": "ok"}, kind="jsonData")
