"""Internal message representation used by the data plane.

The reference keeps every graph edge in wire form (proto or JSON dict)
and re-decodes per node (reference: seldon_methods.py dual-path,
utils.py:558-631).  Here the orchestrator and dispatch layer operate on
one in-memory form, ``InternalMessage``, whose payload may be a numpy
array, a device-resident ``jax.Array``, bytes, str, or a JSON object.
Wire codecs (proto / JSON) run only at transport boundaries, so a chain
of co-located nodes passes device buffers by handle with zero codec
cost — the single biggest latency line-item of the reference deleted.

``kind`` records the wire encoding of the original request so responses
echo it (tensor in -> tensor out), matching reference behaviour
(reference: utils.py:426-498).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu import codec
from seldon_core_tpu.proto import pb

ARRAY_KINDS = ("tensor", "ndarray", "rawTensor", "tftensor")
# tftensor has no REST/JSON representation (TF clients speak gRPC binary);
# JSON responses for tftensor-kind messages fall back to "tensor".
JSON_ARRAY_KINDS = ("tensor", "ndarray", "rawTensor")


@dataclass
class MsgMeta:
    puid: str = ""
    tags: Dict[str, Any] = field(default_factory=dict)
    routing: Dict[str, int] = field(default_factory=dict)
    request_path: Dict[str, str] = field(default_factory=dict)
    metrics: List[Dict[str, Any]] = field(default_factory=list)
    # W3C trace-context carrier ({"traceparent": ..., "tracestate": ...})
    # for hops with no header/metadata channel (native ingress, queue
    # hand-offs, the REST JSON body as a header fallback).  CONSUMED at
    # dispatch (runtime/dispatch.py pops it), so responses never echo
    # the caller's context back downstream.
    trace_context: Dict[str, str] = field(default_factory=dict)

    def copy(self) -> "MsgMeta":
        return MsgMeta(
            puid=self.puid,
            tags=dict(self.tags),
            routing=dict(self.routing),
            request_path=dict(self.request_path),
            metrics=list(self.metrics),
            trace_context=dict(self.trace_context),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.puid:
            out["puid"] = self.puid
        if self.tags:
            out["tags"] = self.tags
        if self.routing:
            out["routing"] = self.routing
        if self.request_path:
            out["requestPath"] = self.request_path
        if self.metrics:
            out["metrics"] = self.metrics
        if self.trace_context:
            out["traceContext"] = self.trace_context
        return out

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "MsgMeta":
        d = d or {}
        return cls(
            puid=d.get("puid", ""),
            tags=dict(d.get("tags", {})),
            routing={k: int(v) for k, v in d.get("routing", {}).items()},
            request_path=dict(d.get("requestPath", {})),
            metrics=list(d.get("metrics", [])),
            trace_context={
                str(k): str(v) for k, v in (d.get("traceContext") or {}).items()
            },
        )


def _metric_to_dict(m: pb.Metric) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "key": m.key,
        "type": pb.Metric.MetricType.Name(m.type),
        "value": m.value,
    }
    if m.tags:
        out["tags"] = dict(m.tags)
    return out


@dataclass
class InternalMessage:
    """One request/response flowing through the graph."""

    payload: Any = None
    names: List[str] = field(default_factory=list)
    kind: str = "tensor"
    meta: MsgMeta = field(default_factory=MsgMeta)
    status: Optional[Dict[str, Any]] = None

    # ---- constructors -----------------------------------------------------

    @classmethod
    def from_proto(cls, msg: pb.SeldonMessage) -> "InternalMessage":
        meta = MsgMeta(
            puid=msg.meta.puid,
            tags=_value_map_to_dict(msg.meta.tags),
            routing=dict(msg.meta.routing),
            request_path=dict(msg.meta.requestPath),
            metrics=[_metric_to_dict(m) for m in msg.meta.metrics],
        )
        kind = codec.message_data_kind(msg)
        payload: Any = None
        names: List[str] = []
        if kind in ARRAY_KINDS:
            payload = codec.datadef_to_array(msg.data)
            names = list(msg.data.names)
        elif kind == "binData":
            payload = msg.binData
        elif kind == "strData":
            payload = msg.strData
        elif kind == "jsonData":
            from google.protobuf import json_format

            payload = json_format.MessageToDict(msg.jsonData)
        status = None
        if msg.HasField("status"):
            s = msg.status
            status = {"status": pb.Status.StatusFlag.Name(s.status)}
            if s.code:
                status["code"] = s.code
            if s.info:
                status["info"] = s.info
            if s.reason:
                status["reason"] = s.reason
        return cls(payload=payload, names=names, kind=kind or "tensor", meta=meta, status=status)

    @classmethod
    def from_json(cls, body: Dict[str, Any]) -> "InternalMessage":
        payload, meta_dict, datadef, kind = codec.extract_json_payload(body)
        names = list(datadef.get("names", [])) if datadef else []
        return cls(
            payload=payload,
            names=names,
            kind=kind,
            meta=MsgMeta.from_dict(meta_dict),
            status=body.get("status"),
        )

    def copy(self) -> "InternalMessage":
        """Isolated copy for concurrent execution paths (shadow traffic):
        meta is deep-copied — every path mutates it (puid assignment,
        requestPath, metrics) — while the payload is shared, since the
        data plane treats payloads as immutable."""
        return InternalMessage(
            payload=self.payload,
            names=list(self.names),
            kind=self.kind,
            meta=self.meta.copy(),
            status=dict(self.status) if self.status else None,
        )

    # ---- exporters --------------------------------------------------------

    def host_payload(self) -> Any:
        """Payload with any device array fetched back to host.  A
        buffer-view payload materialises as its ndarray VIEW (no copy)
        — so the proto/JSON exporters degrade a zero-copy message to
        the ordinary wire encodings without special-casing."""
        if codec.is_device_array(self.payload):
            return codec.from_device(self.payload)
        if isinstance(self.payload, codec.BufferView):
            return self.payload.array()
        return self.payload

    def array(self) -> np.ndarray:
        """Payload as ndarray (fetching from device if needed)."""
        p = self.host_payload()
        if isinstance(p, np.ndarray):
            return p
        return np.asarray(p)

    def to_proto(self) -> pb.SeldonMessage:
        msg = pb.SeldonMessage()
        m = self.meta
        msg.meta.puid = m.puid
        for k, v in m.tags.items():
            _set_value(msg.meta.tags[k], v)
        msg.meta.routing.update(m.routing)
        msg.meta.requestPath.update(m.request_path)
        for md in m.metrics:
            metric = msg.meta.metrics.add()
            metric.key = md.get("key", "")
            metric.type = pb.Metric.MetricType.Value(md.get("type", "COUNTER"))
            metric.value = float(md.get("value", 0.0))
            for tk, tv in (md.get("tags") or {}).items():
                metric.tags[tk] = str(tv)
        if self.status:
            s = self.status
            msg.status.code = int(s.get("code", 0))
            msg.status.info = str(s.get("info", ""))
            msg.status.reason = str(s.get("reason", ""))
            if s.get("status") in ("SUCCESS", "FAILURE"):
                msg.status.status = pb.Status.StatusFlag.Value(s["status"])
        payload = self.host_payload()
        if payload is None:
            return msg
        if isinstance(payload, bytes):
            msg.binData = payload
        elif isinstance(payload, str):
            msg.strData = payload
        elif self.kind == "jsonData" or isinstance(payload, dict):
            from google.protobuf import json_format

            json_format.ParseDict(payload, msg.jsonData)
        else:
            arr = np.asarray(payload)
            kind = self.kind if self.kind in ARRAY_KINDS else "tensor"
            if arr.dtype.kind in "US" and kind != "tftensor":
                kind = "ndarray"  # tftensor carries strings natively (string_val)
            msg.data.CopyFrom(codec.array_to_datadef(arr, self.names, kind))
        return msg

    def to_json(self) -> Dict[str, Any]:
        body: Dict[str, Any] = {}
        if self.status:
            body["status"] = self.status
        meta = self.meta.to_dict()
        if meta:
            body["meta"] = meta
        payload = self.host_payload()
        if payload is None:
            return body
        kind = self.kind if self.kind in JSON_ARRAY_KINDS else "tensor"
        if isinstance(payload, np.ndarray) and payload.dtype.kind in "USO":
            # strings (incl. DT_STRING tftensor decodes: object arrays of
            # bytes) can only travel as ndarray in the JSON dialect
            kind = "ndarray"
        data_body = codec.build_json_payload(
            payload,
            names=self.names,
            data_kind=kind,
        )
        body.update(data_body)
        return body

    def with_payload(self, payload: Any, names: Optional[List[str]] = None) -> "InternalMessage":
        """New message carrying `payload`, inheriting meta/kind."""
        return dataclasses.replace(
            self,
            payload=payload,
            names=list(names) if names is not None else list(self.names),
            meta=self.meta.copy(),
        )


@dataclass
class InternalFeedback:
    request: Optional[InternalMessage] = None
    response: Optional[InternalMessage] = None
    reward: float = 0.0
    truth: Optional[InternalMessage] = None

    @classmethod
    def from_proto(cls, fb: pb.Feedback) -> "InternalFeedback":
        return cls(
            request=InternalMessage.from_proto(fb.request) if fb.HasField("request") else None,
            response=InternalMessage.from_proto(fb.response) if fb.HasField("response") else None,
            reward=fb.reward,
            truth=InternalMessage.from_proto(fb.truth) if fb.HasField("truth") else None,
        )

    @staticmethod
    def _message_from_json(body: Dict[str, Any]) -> InternalMessage:
        """Feedback members may omit the payload entirely: the proto's
        payload oneof can be unset (a meta-only response carrying just
        the routing tags/puid is a legal Feedback shape,
        reference: proto/prediction.proto:77-82), which the strict
        predict-path parser rejects.  Only the genuinely-absent case is
        lenient — a malformed payload (typo'd key, bad dtype) must
        still raise so the client sees 400, not a silent drop."""
        if not any(k in body for k in ("data", "binData", "strData", "jsonData")):
            return InternalMessage(
                payload=None,
                kind="jsonData",
                meta=MsgMeta.from_dict(body.get("meta", {})),
                status=body.get("status"),
            )
        return InternalMessage.from_json(body)

    @classmethod
    def from_json(cls, body: Dict[str, Any]) -> "InternalFeedback":
        return cls(
            request=cls._message_from_json(body["request"]) if "request" in body else None,
            response=cls._message_from_json(body["response"]) if "response" in body else None,
            reward=float(body.get("reward", 0.0)),
            truth=cls._message_from_json(body["truth"]) if "truth" in body else None,
        )

    def to_proto(self) -> pb.Feedback:
        fb = pb.Feedback(reward=self.reward)
        if self.request is not None:
            fb.request.CopyFrom(self.request.to_proto())
        if self.response is not None:
            fb.response.CopyFrom(self.response.to_proto())
        if self.truth is not None:
            fb.truth.CopyFrom(self.truth.to_proto())
        return fb

    def to_json(self) -> Dict[str, Any]:
        body: Dict[str, Any] = {"reward": self.reward}
        if self.request is not None:
            body["request"] = self.request.to_json()
        if self.response is not None:
            body["response"] = self.response.to_json()
        if self.truth is not None:
            body["truth"] = self.truth.to_json()
        return body


# ---------------------------------------------------------------------------

def _value_map_to_dict(value_map) -> Dict[str, Any]:
    from google.protobuf import json_format

    return {k: json_format.MessageToDict(v) for k, v in value_map.items()}


def _set_value(value_pb, v: Any) -> None:
    from google.protobuf import json_format

    json_format.ParseDict(v, value_pb)
