"""REST server for a single graph-node microservice.

aiohttp application exposing the reference wrapper's endpoint surface
(reference: python/seldon_core/wrapper.py:21-98):

    POST /predict  /transform-input  /transform-output
         /route    /aggregate       /send-feedback
    GET  /health/ping  /health/status  /metrics

Requests are JSON bodies (or a ``json`` form/query field, as the
reference accepts).  Payload stays in plain-dict form end-to-end —
no proto round-trip on the REST path.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Callable, Dict, Optional

from aiohttp import web

from seldon_core_tpu.runtime import dispatch
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.runtime.executor_pool import run_dispatch
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage

logger = logging.getLogger(__name__)


def _loads_400(text: Any, what: str) -> Any:
    """json.loads that maps client syntax errors to 400, not 500."""
    try:
        return json.loads(text)
    except json.JSONDecodeError as e:
        raise MicroserviceError(
            f"{what} is not valid JSON: {e}", status_code=400, reason="BAD_REQUEST"
        )


async def _multipart_body(request: web.Request) -> Dict[str, Any]:
    """multipart/form-data request: every top-level message key is a
    form field (reference: flask_utils.get_multi_form_data_request).

    A field means the same thing whether sent as text or as a file
    upload: ``strData`` is taken literally, ``binData`` (file only)
    stays raw bytes, every other key is JSON-parsed.  A lone ``json``
    field carries the whole message."""
    form = await request.post()
    keys = list(form.keys())
    if "json" in keys:
        # whole-message-in-one-field style (the form/query `json`
        # contract, sent as multipart); mixing it with per-key fields
        # is ambiguous and rejected
        if len(keys) > 1:
            raise MicroserviceError(
                "multipart request mixes a 'json' field with message-key fields",
                status_code=400,
                reason="BAD_REQUEST",
            )
        val = form["json"]
        if isinstance(val, web.FileField):  # json=@file.json upload
            val = val.file.read()
        return _loads_400(val, "multipart field 'json'")
    out: Dict[str, Any] = {}
    for key, val in form.items():
        if isinstance(val, web.FileField):
            data = val.file.read()
            if key == "binData":
                out[key] = data
                continue
            try:
                text = data.decode("utf-8")
            except UnicodeDecodeError:
                raise MicroserviceError(
                    f"multipart file field {key!r} is not utf-8 "
                    "(binary payloads go in 'binData')",
                    status_code=400,
                    reason="BAD_REQUEST",
                )
            # a file upload carries the same content its text-field
            # twin would: strData stays literal, JSON keys are parsed
            out[key] = text if key == "strData" else _loads_400(text, f"multipart file field {key!r}")
        elif key == "strData":
            out[key] = val
        else:
            out[key] = _loads_400(val, f"multipart field {key!r}")
    if not out:
        raise MicroserviceError("empty multipart request", status_code=400, reason="BAD_REQUEST")
    return out


async def _request_body(request: web.Request) -> Dict[str, Any]:
    """JSON body, a `json` field in form/query, or multipart fields
    (reference: flask_utils.get_request semantics)."""
    if request.content_type == "application/json":
        return _loads_400(await request.text(), "JSON body")
    if request.content_type and request.content_type.startswith("multipart/form-data"):
        return await _multipart_body(request)
    if request.method == "POST":
        form = await request.post()
        if "json" in form:
            return _loads_400(form["json"], "form field 'json'")
        # raw body fallback
        text = await request.text()
        if text:
            return _loads_400(text, "request body")
    if "json" in request.query:
        return _loads_400(request.query["json"], "query field 'json'")
    raise MicroserviceError("empty request body", status_code=400, reason="BAD_REQUEST")


def _error_response(e: Exception) -> web.Response:
    if isinstance(e, MicroserviceError):
        body = {"status": e.to_status()}
        return web.json_response(body, status=e.status_code)
    from seldon_core_tpu.codec.tensor import PayloadError

    if isinstance(e, PayloadError):
        # undecodable payload is the client's error, not a server fault
        body = {"status": {"status": "FAILURE", "code": 400, "info": str(e),
                           "reason": "BAD_PAYLOAD"}}
        return web.json_response(body, status=400)
    logger.exception("unhandled microservice error")
    body = {"status": {"status": "FAILURE", "code": 500, "info": str(e), "reason": "MICROSERVICE_INTERNAL_ERROR"}}
    return web.json_response(body, status=500)


def _custom_endpoint(user_handler: Callable) -> Callable:
    """Wrap a user custom-route handler: aiohttp Responses pass
    through, anything else JSON-serialises, errors map to Status.
    Sync handlers run on the dispatch pool — they are expected to
    block (that is why the reference isolates them in a second
    process), and must not freeze the event loop."""

    async def handler(request: web.Request) -> web.Response:
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            # custom routes are ingress too (graftlint: propagation):
            # the user handler inherits the caller's trace + budget
            with activate_context(_remote_ctx(request)), \
                    _deadlines.activate_ms(_remote_deadline_ms(request)):
                _deadlines.check(f"microservice ingress {request.path}")
                if asyncio.iscoroutinefunction(user_handler):
                    result = await user_handler(request)
                else:
                    result = await run_dispatch(user_handler, request)
                    if asyncio.iscoroutine(result):  # sync fn returned a coroutine
                        result = await result
            if isinstance(result, web.Response):
                return result
            return web.json_response(result)
        except Exception as e:  # noqa: BLE001
            return _error_response(e)

    return handler


def _remote_ctx(request: web.Request):
    """The caller's W3C span context from the HTTP headers, if tracing
    is on (the body-meta carrier is handled at dispatch).  One global
    read + a header probe when off/absent."""
    from seldon_core_tpu.utils.tracing import extract, get_tracer

    if get_tracer() is None:
        return None
    return extract(request.headers)


def _remote_deadline_ms(request: web.Request):
    """The caller's remaining end-to-end budget from the
    ``X-Seldon-Deadline-Ms`` header (None when absent/malformed)."""
    from seldon_core_tpu.utils import deadlines

    return deadlines.extract_ms(request.headers)


def _message_endpoint(user_model: Any, fn: Callable) -> Callable:
    async def handler(request: web.Request) -> web.Response:
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            body = await _request_body(request)
            msg = InternalMessage.from_json(body)
            # X-Seldon-Adapter selects the LoRA weight set (r16) on the
            # plain microservice lane too — the component reads
            # meta.tags.adapter; an explicit body tag wins, same
            # precedence as the gateway ingress
            adapter = _deadlines.extract_adapter(request.headers)
            if adapter and "adapter" not in msg.meta.tags:
                msg.meta.tags["adapter"] = adapter
            # headers carry the caller's span context; activating it
            # here makes the dispatch span a child of the caller's
            # (run_dispatch copies the context onto the pool thread).
            # The deadline budget rides the same way — and an already-
            # spent budget fails HERE, before the model sees anything.
            with activate_context(_remote_ctx(request)), \
                    _deadlines.activate_ms(_remote_deadline_ms(request)):
                _deadlines.check(f"microservice ingress {request.path}")
                if fn is dispatch.predict:  # async fast path for batched models
                    out = await dispatch.predict_async(user_model, msg)
                else:
                    out = await run_dispatch(fn, user_model, msg)
            return web.json_response(out.to_json())
        except Exception as e:  # noqa: BLE001 — every error must map to a Status
            return _error_response(e)

    return handler


def build_app(
    user_model: Any,
    unit_id: str = "",
    extra_routes: Optional[Dict[str, Callable]] = None,
) -> web.Application:
    app = web.Application(client_max_size=1024 * 1024 * 512)

    async def aggregate_handler(request: web.Request) -> web.Response:
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            body = await _request_body(request)
            raw_list = body.get("seldonMessages", body if isinstance(body, list) else [])
            msgs = [InternalMessage.from_json(b) for b in raw_list]
            with activate_context(_remote_ctx(request)), \
                    _deadlines.activate_ms(_remote_deadline_ms(request)):
                _deadlines.check("microservice ingress /aggregate")
                out = await run_dispatch(dispatch.aggregate, user_model, msgs)
            return web.json_response(out.to_json())
        except Exception as e:  # noqa: BLE001
            return _error_response(e)

    async def feedback_handler(request: web.Request) -> web.Response:
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            body = await _request_body(request)
            fb = InternalFeedback.from_json(body)
            with activate_context(_remote_ctx(request)), \
                    _deadlines.activate_ms(_remote_deadline_ms(request)):
                _deadlines.check("microservice ingress /send-feedback")
                out = await run_dispatch(dispatch.send_feedback, user_model, fb, unit_id)
            return web.json_response(out.to_json())
        except Exception as e:  # noqa: BLE001
            return _error_response(e)

    async def ping(_request: web.Request) -> web.Response:
        return web.Response(text="pong")

    async def status(request: web.Request) -> web.Response:
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            # health dispatch honours the same ingress contract: a
            # probe with a budget fast-fails instead of piling onto a
            # saturated dispatch pool
            with activate_context(_remote_ctx(request)), \
                    _deadlines.activate_ms(_remote_deadline_ms(request)):
                _deadlines.check("microservice ingress /health/status")
                out = await run_dispatch(dispatch.health_check, user_model)
            return web.json_response(out.to_json())
        except Exception as e:  # noqa: BLE001
            return _error_response(e)

    async def metrics_endpoint(_request: web.Request) -> web.Response:
        from prometheus_client import CONTENT_TYPE_LATEST, generate_latest

        return web.Response(body=generate_latest(), content_type=CONTENT_TYPE_LATEST.split(";")[0])

    async def openapi_endpoint(_request: web.Request) -> web.Response:
        from seldon_core_tpu.runtime.openapi import wrapper_openapi

        return web.json_response(wrapper_openapi())

    app.router.add_get("/seldon.json", openapi_endpoint)

    for path, fn in (
        ("/predict", dispatch.predict),
        ("/api/v0.1/predictions", dispatch.predict),  # engine-compatible alias
        ("/transform-input", dispatch.transform_input),
        ("/transform-output", dispatch.transform_output),
        ("/route", dispatch.route),
    ):
        handler = _message_endpoint(user_model, fn)
        app.router.add_post(path, handler)
        app.router.add_get(path, handler)

    app.router.add_post("/aggregate", aggregate_handler)
    app.router.add_get("/aggregate", aggregate_handler)
    app.router.add_post("/send-feedback", feedback_handler)
    app.router.add_get("/send-feedback", feedback_handler)
    app.router.add_get("/health/ping", ping)
    app.router.add_get("/health/status", status)
    app.router.add_get("/metrics", metrics_endpoint)

    for path, handler in (extra_routes or {}).items():
        app.router.add_route("*", path, handler)

    # component-declared endpoints (reference analogue: custom_service
    # second process exposing user routes)
    custom = getattr(user_model, "custom_routes", None)
    if callable(custom):
        for path, user_handler in (custom() or {}).items():
            app.router.add_route("*", path, _custom_endpoint(user_handler))
    return app


async def serve(app: web.Application, host: str = "0.0.0.0", port: int = 9000, tls=None):
    """Run an app until cancelled; returns the runner for cleanup.

    ``tls`` is a utils.tls.TlsConfig; when set the listener terminates
    HTTPS (same files as the gRPC lane)."""
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    ssl_context = None
    if tls is not None and tls.enabled:
        from seldon_core_tpu.utils.tls import server_ssl_context

        ssl_context = server_ssl_context(tls)
    site = web.TCPSite(runner, host, port, ssl_context=ssl_context)
    await site.start()
    return runner
