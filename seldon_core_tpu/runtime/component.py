"""User-facing component API.

``TPUComponent`` is the duck-typed contract a user model implements —
the same surface as the reference's ``SeldonComponent``
(reference: python/seldon_core/user_model.py:20-104): ``predict``,
``transform_input``, ``transform_output``, ``route``, ``aggregate``,
``send_feedback``, plus ``tags``/``metrics``/``class_names`` metadata
hooks and proto-level ``*_raw`` overrides.  Subclassing is optional;
any object with the right methods works (duck typing, like the
reference).

TPU extensions (all optional):

* ``jax_predict()`` — return a pure jax function ``f(params, x) -> y``;
  the serving runtime jits it, pins ``jax_params()`` in HBM, and routes
  requests through the dynamic batcher.
* ``input_signature()`` — (shape, dtype) of one example, used to build
  padding buckets and warm the jit cache at load time.
* ``checkpoint_state()/restore_state(state)`` — pickle-free state
  snapshot hooks used by the persistence subsystem (the reference
  pickles the whole object to Redis; reference: persistence.py:21-84).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np


class MicroserviceError(Exception):
    """Error carried back to the client as a FAILURE Status.

    Equivalent of the reference's SeldonMicroserviceException
    (reference: python/seldon_core/flask_utils.py).
    """

    status_code = 500

    def __init__(self, message: str, status_code: Optional[int] = None, reason: str = "MICROSERVICE_ERROR"):
        super().__init__(message)
        self.message = message
        if status_code is not None:
            self.status_code = status_code
        self.reason = reason

    def to_status(self) -> Dict[str, Any]:
        return {
            "status": "FAILURE",
            "code": self.status_code,
            "info": self.message,
            "reason": self.reason,
        }


class NotImplementedByUser(MicroserviceError):
    """Raised by default method bodies; dispatch treats it as 'fall through'."""

    status_code = 400


class TPUComponent:
    """Base class for models / routers / transformers / combiners."""

    # True for components whose load() pins a TPU device: libtpu binds
    # one process per chip, so such components cannot be replicated as
    # subprocesses (the control plane's hpa guard reads this — scale
    # batcher/worker concurrency in-process instead)
    device_exclusive: bool = False

    def __init__(self, **kwargs: Any):
        pass

    # ---- lifecycle --------------------------------------------------------

    def load(self) -> None:
        """Heavy initialisation: download weights, compile, warm up."""

    # ---- metadata hooks ---------------------------------------------------

    def tags(self) -> Dict:
        raise NotImplementedByUser("tags not implemented")

    def metrics(self) -> List[Dict]:
        raise NotImplementedByUser("metrics not implemented")

    def class_names(self) -> Iterable[str]:
        raise NotImplementedByUser("class_names not implemented")

    def feature_names(self) -> Iterable[str]:
        raise NotImplementedByUser("feature_names not implemented")

    # ---- node-role methods ------------------------------------------------

    def predict(self, X: np.ndarray, names: Iterable[str], meta: Optional[Dict] = None):
        raise NotImplementedByUser("predict not implemented")

    def transform_input(self, X: np.ndarray, names: Iterable[str], meta: Optional[Dict] = None):
        raise NotImplementedByUser("transform_input not implemented")

    def transform_output(self, X: np.ndarray, names: Iterable[str], meta: Optional[Dict] = None):
        raise NotImplementedByUser("transform_output not implemented")

    def route(self, features: Union[np.ndarray, str, bytes], feature_names: Iterable[str]) -> int:
        raise NotImplementedByUser("route not implemented")

    def aggregate(self, features_list: List, feature_names_list: List):
        raise NotImplementedByUser("aggregate not implemented")

    def send_feedback(
        self,
        features: Union[np.ndarray, str, bytes],
        feature_names: Iterable[str],
        reward: float,
        truth,
        routing: Optional[int],
    ):
        raise NotImplementedByUser("send_feedback not implemented")

    # ---- state hooks (persistence subsystem) ------------------------------

    def checkpoint_state(self) -> Optional[Dict[str, Any]]:
        """Return a JSON/array tree snapshot of mutable state, or None."""
        return None

    def restore_state(self, state: Dict[str, Any]) -> None:
        pass

    # ---- custom serving surface (optional) --------------------------------

    def custom_routes(self) -> Dict[str, Any]:
        """Extra REST endpoints merged into the microservice app:
        ``{path: handler}`` where a handler is either an aiohttp
        handler (async, returns a Response) or a plain callable whose
        JSON-serialisable return value becomes the response body.
        Covers the reference's custom-endpoint pattern
        (reference: examples/models/mean_classifier_with_custom_endpoints)
        without a second server process.

        A component may also define ``custom_service()`` — a blocking
        side loop the CLI runs on a daemon thread at startup (the
        reference runs it as a second process,
        reference: microservice.py:29-47,363-368).  Deliberately NOT
        defined here: its presence is detected by ``hasattr``, so a
        base-class stub would make every component look like it has
        one."""
        return {}


# ---------------------------------------------------------------------------
# duck-typed accessors (reference: user_model.py client_* helpers)
# ---------------------------------------------------------------------------

def _call_optional(user_model: Any, name: str, *args, **kwargs):
    fn = getattr(user_model, name, None)
    if fn is None:
        return None
    try:
        return fn(*args, **kwargs)
    except NotImplementedByUser:
        return None


def get_custom_tags(user_model: Any) -> Dict:
    return _call_optional(user_model, "tags") or {}


def get_custom_metrics(user_model: Any) -> Optional[List[Dict]]:
    metrics = _call_optional(user_model, "metrics")
    if metrics is None:
        return None
    if not validate_metrics(metrics):
        raise MicroserviceError(
            f"invalid metrics returned by component: {metrics!r}", status_code=500, reason="INVALID_METRICS"
        )
    return metrics


def get_class_names(user_model: Any, n_columns: Optional[int] = None) -> List[str]:
    names = _call_optional(user_model, "class_names")
    if names is not None:
        return list(names)
    return []


def get_feature_names(user_model: Any) -> List[str]:
    names = _call_optional(user_model, "feature_names")
    return list(names) if names is not None else []


# ---------------------------------------------------------------------------
# custom-metric helpers (reference: python/seldon_core/metrics.py:1-93)
# ---------------------------------------------------------------------------

COUNTER = "COUNTER"
GAUGE = "GAUGE"
TIMER = "TIMER"
_METRIC_TYPES = (COUNTER, GAUGE, TIMER)


def counter_metric(key: str, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> Dict:
    m = {"key": key, "type": COUNTER, "value": float(value)}
    if tags:
        m["tags"] = tags
    return m


def gauge_metric(key: str, value: float, tags: Optional[Dict[str, str]] = None) -> Dict:
    m = {"key": key, "type": GAUGE, "value": float(value)}
    if tags:
        m["tags"] = tags
    return m


def timer_metric(key: str, value_ms: float, tags: Optional[Dict[str, str]] = None) -> Dict:
    m = {"key": key, "type": TIMER, "value": float(value_ms)}
    if tags:
        m["tags"] = tags
    return m


def validate_metrics(metrics: Any) -> bool:
    if not isinstance(metrics, list):
        return False
    for m in metrics:
        if not isinstance(m, dict):
            return False
        if not {"key", "type", "value"} <= m.keys():
            return False
        if m["type"] not in _METRIC_TYPES:
            return False
        if not isinstance(m["value"], (int, float)) or isinstance(m["value"], bool):
            return False
    return True
