"""Model-runtime layer: component API, dispatch, servers, CLI."""

from seldon_core_tpu.runtime.component import (  # noqa: F401
    MicroserviceError,
    NotImplementedByUser,
    TPUComponent,
    counter_metric,
    gauge_metric,
    timer_metric,
    validate_metrics,
)
from seldon_core_tpu.runtime.message import (  # noqa: F401
    InternalFeedback,
    InternalMessage,
    MsgMeta,
)
