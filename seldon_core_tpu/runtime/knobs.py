"""Central registry of every runtime tuning knob.

Every ``SELDON_TPU_*`` environment variable, every ``seldon.io/*``
deployment annotation and every ``X-Seldon-*`` request header the
package reads is DECLARED here — name, type, default, whether ``=0``
spells OFF, one line of doc, and the docs section that explains it.
The registry is load-bearing three ways:

* **Reads go through it.**  :func:`raw` / :func:`flag` are the only
  sanctioned ways to read a ``SELDON_TPU_*`` env var inside
  ``seldon_core_tpu/`` — they raise :class:`UndeclaredKnobError` for a
  name that is not registered, so a knob cannot exist without an entry
  (and therefore without docs).  ``tools/graftlint``'s knob-registry
  checker enforces the same invariant statically: a direct
  ``os.environ`` read of a ``SELDON_TPU_*`` literal anywhere outside
  this module fails the lint.

* **``=0`` spells OFF.**  A PR 7 review caught ``SELDON_TPU_TP=0``
  crashing engine load; the fleet-wide convention since is that ``=0``
  on any knob means "feature off", never an error.  ``zero_off``
  records which knobs carry that contract so the lint and the tests
  can police it.

* **It is an operational surface.**  :func:`snapshot` renders the
  whole registry with current effective values — the gateway serves it
  at ``GET /debug/knobs`` so "what is this process actually running
  with" is one curl, not a grep.

The module is import-light on purpose (stdlib only): utils modules read
knobs from hot-ish paths and must not drag the serving stack in.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

__all__ = [
    "Knob",
    "Annotation",
    "Header",
    "ENV_KNOBS",
    "ANNOTATIONS",
    "HEADERS",
    "UndeclaredKnobError",
    "raw",
    "flag",
    "declared",
    "snapshot",
]


class UndeclaredKnobError(KeyError):
    """A ``SELDON_TPU_*`` read of a name missing from the registry —
    a programming error (declare the knob), never a runtime condition."""


@dataclass(frozen=True)
class Knob:
    """One declared environment knob.

    ``kind`` is documentation of the accepted value shape (``flag`` |
    ``int`` | ``float`` | ``str`` | ``path`` | ``spec``); parsing stays
    at the read site so migration to the registry is behaviour-
    identical.  ``default`` is the effective value when unset, as the
    reader interprets it.  ``zero_off`` declares the ``=0``-means-OFF
    contract.  ``anchor`` names the docs section that documents the
    knob (the lint additionally requires the knob name to appear in
    ``docs/``)."""

    name: str
    kind: str
    default: str
    zero_off: bool
    doc: str
    anchor: str


@dataclass(frozen=True)
class Annotation:
    """One declared ``seldon.io/*`` deployment annotation."""

    name: str
    kind: str
    doc: str


@dataclass(frozen=True)
class Header:
    """One declared ``X-Seldon-*`` request header (case-insensitive on
    the wire; gRPC metadata uses the lowercase spelling)."""

    name: str
    kind: str
    doc: str


def _knobs(*knobs: Knob) -> Dict[str, Knob]:
    out: Dict[str, Knob] = {}
    for k in knobs:
        if k.name in out:
            raise ValueError(f"duplicate knob declaration {k.name!r}")
        out[k.name] = k
    return out


ENV_KNOBS: Dict[str, Knob] = _knobs(
    # ---- runtime / process ------------------------------------------------
    Knob("SELDON_TPU_PLATFORM", "str", "", False,
         "force the jax platform (cpu|tpu|...) for the microservice CLI",
         "operations.md"),
    Knob("SELDON_TPU_DISPATCH_THREADS", "int", "128", False,
         "dispatch thread-pool size for component calls",
         "architecture.md §2"),
    Knob("SELDON_TPU_TRACE_EXPORT", "path", "", False,
         "per-process JSONL span sink (tools/profile_trace_stitch.py reads it)",
         "architecture.md §5c-bis"),
    Knob("SELDON_TPU_DRAIN_JOURNAL", "path", "", False,
         "drain/handoff journal path (pinned per worker by the supervisor)",
         "operations.md failure-containment"),
    Knob("SELDON_TPU_MODEL_CACHE", "path", "", False,
         "model-artifact download cache directory (default: tmpdir)",
         "architecture.md §3"),
    Knob("SELDON_TPU_NATIVE_SO", "path", "", False,
         "override the native front-server shared object (TSan/ASan builds)",
         "architecture.md §9"),
    Knob("SELDON_TPU_NATIVE_BATCH_THREADS", "int", "4", False,
         "native ingress batch-submit thread count",
         "architecture.md §9"),
    Knob("SELDON_TPU_NATIVE_RAW_WORKERS", "int", "8", False,
         "native ingress raw/gRPC fallback worker count",
         "architecture.md §9"),
    # ---- transport / telemetry -------------------------------------------
    Knob("SELDON_TPU_ZERO_COPY", "flag", "1", True,
         "buffer-view SeldonMessage lane: SRT1 frames decode to zero-copy "
         "views from native ingress to device buffers (0 = proto/JSON "
         "path only, behaviour-identical to the pre-lane engine)",
         "architecture.md §9a"),
    Knob("SELDON_TPU_BREAKER", "flag", "1", True,
         "per-endpoint circuit breakers (0 = off; breaker-off is "
         "byte-identical to the pre-breaker transport)",
         "operations.md failure-containment"),
    Knob("SELDON_TPU_TRANSPORT_TELEMETRY", "flag", "1", True,
         "per-hop transport metrics (0 = off; the bench's trace_prop "
         "contrast flips this)",
         "architecture.md §5c-bis"),
    Knob("SELDON_TPU_FAULT", "spec", "", True,
         "fault-injection spec 'point[:k=v,..];..' (empty/0 = disarmed)",
         "operations.md fault-injection"),
    # ---- generation engine ------------------------------------------------
    Knob("SELDON_TPU_TP", "int", "0", True,
         "tensor-parallel degree over the 'model' mesh axis "
         "(unset/empty/0 = single-chip)",
         "architecture.md §5b-ter"),
    Knob("SELDON_TPU_DP", "int", "0", True,
         "data-parallel degree over the 'data' mesh axis of the 2-D "
         "serving mesh (unset/empty/0 = one replica group)",
         "architecture.md §5b-octies"),
    Knob("SELDON_TPU_SEQ_SHARD", "flag", "1", True,
         "shard the KV pool's page dim over the 'data' axis (sequence/"
         "long-context sharding; 0 = replicate the pool — pure "
         "throughput replicas, no capacity claim)",
         "architecture.md §5b-octies"),
    Knob("SELDON_TPU_PAGED_KERNEL", "str", "auto", True,
         "pallas decode-kernel lane ('0' | '1' | 'auto' | 'force'; "
         "default 'auto' = on for single-chip TPU backends, off "
         "elsewhere — '0' restores the XLA gather lane byte-for-byte)",
         "architecture.md §5b-septies"),
    Knob("SELDON_TPU_PAGED_KERNEL_IMPL", "str", "stream", False,
         "pallas decode kernel implementation ('stream' | 'grid')",
         "architecture.md §5b"),
    Knob("SELDON_TPU_KV_DTYPE", "str", "bf16", False,
         "KV pool element dtype ('bf16' | 'int8'); int8 stores pages "
         "quantised with one f32 scale per page per k/v in a sibling "
         "scale table — halves pool bytes, single-chip pool-impl only",
         "architecture.md §5b-septies"),
    Knob("SELDON_TPU_CHUNK_IMPL", "str", "", False,
         "chunk program implementation ('ring' | 'pool'; empty = auto)",
         "architecture.md §5b"),
    Knob("SELDON_TPU_CTX_BUCKETS", "int", "2", False,
         "context-length buckets per chunk program ('1' disables, '2' default)",
         "architecture.md §5b"),
    Knob("SELDON_TPU_PREFIX_CACHE", "flag", "1", True,
         "page-granular automatic prefix caching (0 = off)",
         "architecture.md §5b-bis"),
    Knob("SELDON_TPU_PAGED_DEBUG", "flag", "0", False,
         "chunk-boundary allocator state-machine audit (1 = on)",
         "architecture.md §5b-bis"),
    Knob("SELDON_TPU_MAX_QUEUE", "int", "0", True,
         "bounded run-queue depth for priority shedding (0 = unbounded)",
         "operations.md overload-runbook"),
    Knob("SELDON_TPU_CHUNK_TOKEN_BUDGET", "int", "0", True,
         "chunked-prefill co-scheduling: max tokens one engine wave may "
         "carry, filled decode-first then with page-aligned prompt "
         "slices (0 = off: monolithic prefill, the historical engine)",
         "architecture.md §5b-quater"),
    Knob("SELDON_TPU_PREFILL_WORKERS", "int", "0", True,
         "disaggregated serving: dedicated prefill workers streaming "
         "finished KV pages into the decode engine's pool (0 = off: "
         "unified prefill+decode engine)",
         "architecture.md §5b-quater"),
    Knob("SELDON_TPU_DISAGG_ROLE", "str", "", False,
         "role pin for supervisor-spawned disaggregated workers "
         "('prefill' | 'decode'; empty = unified engine)",
         "architecture.md §5b-quater"),
    Knob("SELDON_TPU_ADMISSION_PRICING", "flag", "1", True,
         "disaggregated admission prices a request by predicted "
         "prefill+decode cost and fast-fails deadlines it cannot meet "
         "(0 = admit everything, price nothing)",
         "architecture.md §5b-quater"),
    Knob("SELDON_TPU_MAX_ADAPTERS", "int", "0", True,
         "multi-LoRA serving: adapter slots in the engine's factor "
         "pool (0 = adapters off, byte-identical pre-adapter programs)",
         "architecture.md §5b-quinquies"),
    Knob("SELDON_TPU_WEIGHT_BUDGET_GIB", "float", "0", True,
         "HBM budget for the process weight registry's named weight "
         "sets (base models + LoRA adapters; 0 = unbudgeted loads)",
         "architecture.md §5b-quinquies"),
    Knob("SELDON_TPU_KV_CHECKSUM", "flag", "1", True,
         "CRC32C integrity trailer on KV handoff/migration containers "
         "(0 = off; default on — a flipped DCN byte rejects as a named "
         "PayloadError instead of decoding as garbage KV)",
         "architecture.md §5b-sexies"),
    Knob("SELDON_TPU_KV_OFFLOAD", "flag", "0", True,
         "hierarchical KV tier: demote LRU-reclaimed prefix/session "
         "pages into a budgeted host-RAM store (optionally spilling to "
         "disk) and promote them back through the donated-scatter "
         "import on the next chain hit (0 = off, byte-identical "
         "programs and discard-on-reclaim as before)",
         "architecture.md §5b-nonies"),
    Knob("SELDON_TPU_KV_HOST_BUDGET_GIB", "float", "4", False,
         "host-RAM byte budget for the KV tier's container store "
         "(oldest entries spill to disk or drop when exceeded)",
         "architecture.md §5b-nonies"),
    Knob("SELDON_TPU_KV_SPILL_DIR", "path", "", False,
         "disk level below the host KV tier: CRC-trailered containers "
         "written atomic tmp+rename, LRU-evicted to the spill budget "
         "(empty = no disk level, host-budget overflow drops)",
         "architecture.md §5b-nonies"),
    Knob("SELDON_TPU_KV_SPILL_GIB", "float", "16", False,
         "disk byte budget for the KV tier's spill directory",
         "architecture.md §5b-nonies"),
    Knob("SELDON_TPU_NAN_GUARD", "flag", "1", True,
         "post-chunk NaN/Inf screen on served logits: a non-finite lane "
         "retires ONLY its stream with 500 NUMERIC_POISON (0 = off; "
         "decode lane only — speculative verify emits argmax ids, its "
         "logits never reach the host)",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_WATCHDOG", "flag", "1", True,
         "device-health watchdog driving the engine health state "
         "machine healthy -> degraded -> evacuating (0 = off)",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_WATCHDOG_CHUNK_MS", "float", "0", True,
         "chunk-wall-time ceiling (ms) the watchdog counts breaches "
         "against; compile waves are exempt (0 = ceiling off)",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_WATCHDOG_FAULT_RATE", "float", "0.5", False,
         "chunk-fault fraction of the watchdog window that degrades "
         "the engine",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_WATCHDOG_COMPILES", "int", "0", True,
         "jit-compile storm threshold per watchdog window under "
         "traffic (0 = off; first-chunk cold compiles never count)",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_WATCHDOG_HBM_PCT", "float", "0", True,
         "pool-page occupancy percentage counted as allocator "
         "pressure by the watchdog (0 = off)",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_WATCHDOG_WINDOW", "int", "32", False,
         "watchdog sliding-window length in engine waves",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_WATCHDOG_BREACHES", "int", "8", False,
         "window breaches that drive healthy -> degraded (a clean "
         "window recovers degraded -> healthy)",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_FORCE_EVACUATE", "flag", "0", False,
         "force the engine health state to 'evacuating' (operator "
         "forced-migration switch; 1 = on)",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_EVACUATE_TO", "str", "", False,
         "peer endpoint ('grpc://host:port' | 'rest://host:port') that "
         "drain() live-migrates streams to before exiting; failures "
         "fall back to the drain journal (empty = journal only)",
         "operations.md evacuation-runbook"),
    Knob("SELDON_TPU_JIT_SENTINEL", "flag", "1", True,
         "XLA recompile sentinel on engine jit entry points (0 = off)",
         "architecture.md §5c"),
    Knob("SELDON_TPU_PROM_BRIDGE", "flag", "1", True,
         "auto-wired GenerationPrometheusBridge in StreamingLM.load (0 = off)",
         "architecture.md §5c"),
    # ---- observability / forensics ---------------------------------------
    Knob("SELDON_TPU_FLIGHT_RECORDER", "str", "512", True,
         "per-chunk flight-recorder ring capacity (0 = off, digits = size)",
         "architecture.md §5c"),
    Knob("SELDON_TPU_DUMP_P99_MS", "float", "0", True,
         "chunk-wall p99 breach threshold that auto-dumps the ring (0 = off)",
         "architecture.md §5c"),
    Knob("SELDON_TPU_DUMP_DIR", "path", "", False,
         "directory for p99-breach flight-recorder JSONL dumps",
         "architecture.md §5c"),
    Knob("SELDON_TPU_PROFILE_DIR", "path", "", False,
         "jax.profiler trace output dir for the first N decode chunks",
         "architecture.md §5c"),
    Knob("SELDON_TPU_PROFILE_CHUNKS", "int", "4", False,
         "how many decode chunks run under the profiler hook",
         "architecture.md §5c"),
    # ---- fleet telemetry plane (r20) --------------------------------------
    Knob("SELDON_TPU_TELEMETRY", "flag", "1", True,
         "fleet telemetry plane: per-replica telemetry ring, per-request "
         "cost ledger and histogram trace exemplars (0 = off, behaviour-"
         "identical to the pre-telemetry build — no new metric series)",
         "architecture.md §5c-ter"),
    Knob("SELDON_TPU_TELEMETRY_RING", "int", "256", False,
         "telemetry time-series ring capacity (samples per replica)",
         "architecture.md §5c-ter"),
    Knob("SELDON_TPU_FLEET_ENDPOINTS", "str", "", True,
         "comma-separated replica base URLs (name=http://host:port,...) "
         "the gateway's fleet aggregator polls for /debug/fleet (empty/0 "
         "= derive from the local supervisor's workers, else fleet view "
         "off)",
         "architecture.md §5c-ter"),
    Knob("SELDON_TPU_FLEET_POLL_S", "float", "2", False,
         "fleet aggregator poll interval (seconds)",
         "architecture.md §5c-ter"),
    Knob("SELDON_TPU_FLEET_STALE_S", "float", "10", False,
         "age after which a non-responding replica's fleet entry is "
         "marked stale (it keeps its last snapshot; the poll loop never "
         "fails over one dead replica)",
         "architecture.md §5c-ter"),
    # ---- per-request black-box capture + replay forensics (r21) -----------
    Knob("SELDON_TPU_CAPTURE", "flag", "0", True,
         "per-request black-box capture plane: head-sampled / on-error / "
         "p99-breach requests are serialized as SRT1 capture containers "
         "for GET /debug/request/<puid> and tools/seldon_replay.py "
         "(0 = off, bit-exact pre-capture serving and no new stats keys)",
         "architecture.md §5c-quater"),
    Knob("SELDON_TPU_CAPTURE_SAMPLE", "int", "0", True,
         "head-sampling rate: capture every Nth completed request "
         "(0 = no head sampling; error/breach triggers still fire when "
         "the capture plane is on)",
         "architecture.md §5c-quater"),
    Knob("SELDON_TPU_CAPTURE_DIR", "path", "", False,
         "bounded on-disk capture store directory (LRU-by-bytes "
         "eviction); empty = per-process temp directory",
         "architecture.md §5c-quater"),
    Knob("SELDON_TPU_CAPTURE_PAYLOADS", "flag", "1", True,
         "keep ingress/output payload frames in capture containers; 0 = "
         "capture.redact drops prompt/token ids (lengths and metadata "
         "survive, replay becomes impossible — the privacy posture)",
         "architecture.md §5c-quater"),
)


def _annotations(*anns: Annotation) -> Dict[str, Annotation]:
    return {a.name: a for a in anns}


ANNOTATIONS: Dict[str, Annotation] = _annotations(
    Annotation("seldon.io/frontend", "str",
               "gateway frontend selection (e.g. 'native')"),
    Annotation("seldon.io/breaker", "flag",
               "per-deployment circuit-breaker enable/disable"),
    Annotation("seldon.io/breaker-failures", "int",
               "consecutive transient failures that open the breaker"),
    Annotation("seldon.io/breaker-reset-ms", "int",
               "open -> half-open probe delay"),
    Annotation("seldon.io/breaker-probes", "int",
               "half-open probe budget"),
    Annotation("seldon.io/hedge-ms", "int",
               "first-wins duplicate delay for idempotent unary calls"),
    Annotation("seldon.io/grpc-retries", "int",
               "bounded gRPC retry budget on transient statuses"),
    Annotation("seldon.io/grpc-read-timeout", "int",
               "gRPC per-call timeout (ms)"),
    Annotation("seldon.io/rest-retries", "int",
               "bounded REST retry budget on 502/503/504 + connection faults"),
    Annotation("seldon.io/rest-read-timeout", "int",
               "REST read timeout (ms)"),
    Annotation("seldon.io/rest-connection-timeout", "int",
               "REST connect timeout (ms)"),
    Annotation("seldon.io/worker-ready-timeout-s", "float",
               "supervised remote-worker readiness deadline"),
    Annotation("seldon.io/oauth-key", "str", "gateway OAuth client key"),
    Annotation("seldon.io/oauth-secret", "str", "gateway OAuth client secret"),
    Annotation("seldon.io/oauth-token-ttl-s", "int", "OAuth token lifetime"),
    Annotation("seldon.io/tls-cert", "path", "TLS certificate file"),
    Annotation("seldon.io/tls-key", "path", "TLS private-key file"),
    Annotation("seldon.io/tls-ca", "path", "TLS CA bundle for client auth"),
    Annotation("seldon.io/tls-require-client-auth", "flag",
               "require mTLS client certificates"),
    Annotation("seldon.io/request-log-url", "str",
               "request/response logger HTTP sink"),
    Annotation("seldon.io/request-log-jsonl", "path",
               "request/response logger JSONL sink"),
    Annotation("seldon.io/request-log-kafka", "str",
               "request/response logger Kafka sink (broker/topic)"),
    Annotation("seldon.io/request-logger", "str",
               "gateway-level request/response pair logger sink spec: "
               "http(s)://url | kafka:brokers/topic | a JSONL file path "
               "(pairs stamped with puid + traceparent + cost)"),
)


HEADERS: Dict[str, Header] = {
    h.name: h for h in (
        Header("X-Seldon-Deadline-Ms", "int",
               "end-to-end budget minted at ingress; re-injected with the "
               "remaining budget on every downstream hop"),
        Header("X-Seldon-Priority", "int",
               "admission priority class for the generation engine's "
               "shedding/preemption policy"),
        Header("X-Seldon-Adapter", "str",
               "named LoRA adapter (weight set) this request decodes "
               "with; lands in meta.tags.adapter — an explicit tag in "
               "the body wins"),
    )
}

# lowercase alias set for gRPC-metadata spellings: the wire carries
# either case, the registry declares each header once
_HEADER_NAMES_LOWER = {h.lower() for h in HEADERS}


def declared(name: str) -> bool:
    """True when ``name`` is a registered env knob, annotation, or
    header (headers match case-insensitively)."""
    return (
        name in ENV_KNOBS
        or name in ANNOTATIONS
        or name.lower() in _HEADER_NAMES_LOWER
    )


def _require(name: str) -> Knob:
    knob = ENV_KNOBS.get(name)
    if knob is None:
        raise UndeclaredKnobError(
            f"{name!r} is not declared in runtime/knobs.py — every "
            "SELDON_TPU_* env read must go through the registry"
        )
    return knob


def raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """Registered passthrough for ``os.environ.get(name, default)``.

    Parsing stays at the call site (the registry's ``kind``/``default``
    fields are documentation + the /debug/knobs surface), so migrating
    a read here is behaviour-identical by construction."""
    _require(name)
    return os.environ.get(name, default)


def flag(name: str) -> bool:
    """The canonical on/off read: ``=0`` spells OFF, anything else
    (including unset, for default-on knobs) follows the declared
    default.  Only valid for knobs registered with kind='flag'."""
    knob = _require(name)
    if knob.kind != "flag":
        raise UndeclaredKnobError(
            f"{name!r} is kind={knob.kind!r}, not a flag — read it with "
            "knobs.raw() and parse at the call site"
        )
    val = os.environ.get(name)
    if val is None:
        val = knob.default
    if knob.default == "1":
        return val != "0"  # default-on: =0 spells OFF
    return val == "1"  # default-off: =1 spells ON


def snapshot(environ: Optional[Dict[str, str]] = None) -> List[Dict[str, Any]]:
    """The whole env-knob registry with current raw values — the
    ``GET /debug/knobs`` payload.  ``environ`` overrides the process
    environment (tests)."""
    e = environ if environ is not None else os.environ
    out: List[Dict[str, Any]] = []
    for knob in sorted(ENV_KNOBS.values(), key=lambda k: k.name):
        val = e.get(knob.name)
        out.append({
            "name": knob.name,
            "kind": knob.kind,
            "default": knob.default,
            "zero_off": knob.zero_off,
            "set": val is not None,
            "value": val,
            "doc": knob.doc,
            "anchor": knob.anchor,
        })
    return out
