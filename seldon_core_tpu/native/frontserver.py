"""ctypes bindings for the native C++ front server.

The C++ ingress (``native/frontserver.cc``) owns the HTTP hot path —
accept, parse, payload decode, dynamic batching, response serialisation
— and calls back into Python exactly once per coalesced *batch* (the
model call), or per request on the fallback lane (full engine
semantics for payloads the fast lane cannot express).  This mirrors the
reference's decision to keep the request path out of the model-language
runtime (the Java engine; reference: doc/source/graph/svcorch.md:1-8).

Two callback surfaces:

* ``model_fn(batch[rows, cols] f32|u8) -> [rows, out_dim]`` — the fast
  lane.  For a JaxServer this is the jit-compiled apply; the GIL is
  taken once per batch and released during XLA execution.  The server
  runs ``batch_threads`` workers, so model_fn must be thread-safe —
  concurrent calls pipeline device batches (throughput = in-flight
  depth x batch / device roundtrip when the link latency dominates).
* ``raw_handler(method, path, body) -> (status, content_type, body)``
  — the fallback lane, typically ``GatewayRawHandler`` bridging into
  the deployment's asyncio engine.
"""

from __future__ import annotations

import ctypes
import json
import logging
import threading
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from seldon_core_tpu.native import get_lib

logger = logging.getLogger(__name__)

_BATCH_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32,
    ctypes.c_void_p,                  # ctx
    ctypes.c_void_p,                  # in ([rows*cols] of dtype)
    ctypes.c_int64,                   # rows
    ctypes.c_int64,                   # cols
    ctypes.c_int32,                   # dtype: 0=f32 1=u8
    ctypes.POINTER(ctypes.c_float),   # out
    ctypes.c_int64,                   # out_cols
)

_RAW_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32,
    ctypes.c_void_p,                          # ctx
    ctypes.c_char_p,                          # method
    ctypes.c_char_p,                          # path
    ctypes.POINTER(ctypes.c_uint8),           # body
    ctypes.c_int64,                           # body_len
    ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),  # out_buf
    ctypes.POINTER(ctypes.c_int64),           # out_len
    ctypes.POINTER(ctypes.c_int32),           # http_status
    ctypes.POINTER(ctypes.c_char),            # content_type[64] — must be
    # a writable pointer: c_char_p would hand the callback an immutable
    # bytes copy and the C buffer would never see the write
)


_GRPC_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32,
    ctypes.c_void_p,                          # ctx
    ctypes.c_char_p,                          # path
    ctypes.POINTER(ctypes.c_uint8),           # msg
    ctypes.c_int64,                           # msg_len
    ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),  # out_buf
    ctypes.POINTER(ctypes.c_int64),           # out_len
    ctypes.POINTER(ctypes.c_int32),           # grpc_status
    ctypes.POINTER(ctypes.c_char),            # grpc_msg[256] (writable)
)

_GRPC_STREAM_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32,
    ctypes.c_void_p,                          # ctx
    ctypes.c_char_p,                          # path
    ctypes.POINTER(ctypes.c_uint8),           # msg
    ctypes.c_int64,                           # msg_len
    ctypes.c_uint64,                          # stream_handle
)


class _FsConfig(ctypes.Structure):
    _fields_ = [
        ("port", ctypes.c_int32),
        ("max_batch", ctypes.c_int32),
        ("max_wait_us", ctypes.c_int32),
        ("feature_dim", ctypes.c_int32),
        ("out_dim", ctypes.c_int32),
        ("stub_mode", ctypes.c_int32),
        ("raw_workers", ctypes.c_int32),
        ("backlog", ctypes.c_int32),
        ("eager_when_idle", ctypes.c_int32),
        ("batch_threads", ctypes.c_int32),
        ("model_name", ctypes.c_char_p),
        ("names_csv", ctypes.c_char_p),
        ("buckets_csv", ctypes.c_char_p),
        ("bind_host", ctypes.c_char_p),
    ]


class _FsStats(ctypes.Structure):
    _fields_ = [
        ("requests", ctypes.c_int64),
        ("fast_requests", ctypes.c_int64),
        ("raw_requests", ctypes.c_int64),
        ("batches", ctypes.c_int64),
        ("rows", ctypes.c_int64),
        ("padded_rows", ctypes.c_int64),
        ("failures", ctypes.c_int64),
        ("connections", ctypes.c_int64),
        ("dropped_orphans", ctypes.c_int64),
    ]


_FS_BOUND = False


def _bind(lib) -> None:
    global _FS_BOUND
    if _FS_BOUND:
        return
    lib.fs_create.restype = ctypes.c_void_p
    lib.fs_create.argtypes = [ctypes.POINTER(_FsConfig)]
    lib.fs_destroy.argtypes = [ctypes.c_void_p]
    lib.fs_set_batch_handler.argtypes = [ctypes.c_void_p, _BATCH_CB, ctypes.c_void_p]
    lib.fs_set_raw_handler.argtypes = [ctypes.c_void_p, _RAW_CB, ctypes.c_void_p]
    if hasattr(lib, "fs_set_grpc_handler"):  # older .so builds lack the lane
        lib.fs_set_grpc_handler.argtypes = [ctypes.c_void_p, _GRPC_CB, ctypes.c_void_p]
        lib.fs_set_grpc_stream_handler.argtypes = [
            ctypes.c_void_p, _GRPC_STREAM_CB, ctypes.c_void_p
        ]
        lib.fs_stream_push.restype = ctypes.c_int64
        lib.fs_stream_push.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ]
        lib.fs_stream_close.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32, ctypes.c_char_p
        ]
    lib.fs_start.restype = ctypes.c_int32
    lib.fs_start.argtypes = [ctypes.c_void_p]
    lib.fs_stop.argtypes = [ctypes.c_void_p]
    lib.fs_port.restype = ctypes.c_int32
    lib.fs_port.argtypes = [ctypes.c_void_p]
    lib.fs_set_ready.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.fs_get_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(_FsStats)]
    lib.fs_alloc.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.fs_alloc.argtypes = [ctypes.c_int64]
    _FS_BOUND = True


def available() -> bool:
    lib = get_lib()
    return lib is not None and hasattr(lib, "fs_create")


RawHandler = Callable[[str, str, bytes], Tuple[int, str, bytes]]
# (path, request_proto_bytes) -> (grpc_status, grpc_message, response_proto)
GrpcHandler = Callable[[str, bytes], Tuple[int, str, bytes]]
# (path, request_proto_bytes, stream_handle) -> 0 to accept; the handler
# spawns its own producer thread and pushes via server.stream_push /
# server.stream_close
GrpcStreamHandler = Callable[[str, bytes, int], int]


class NativeFrontServer:
    """The C++ data-plane ingress, driven from Python.

    stub mode (``model_fn=None, stub=True``) serves a fixed-output
    model entirely in C++ — the reference's SIMPLE_MODEL benchmarking
    methodology (reference: doc/source/reference/benchmarking.md:19-36)
    for measuring the serving plane itself.
    """

    def __init__(
        self,
        port: int = 0,
        model_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        feature_dim: int = 0,
        out_dim: int = 3,
        stub: bool = False,
        max_batch: int = 64,
        max_wait_ms: float = 1.0,
        model_name: str = "model",
        names: Optional[Sequence[str]] = None,
        raw_handler: Optional[RawHandler] = None,
        grpc_handler: Optional[GrpcHandler] = None,
        grpc_stream_handler: Optional[GrpcStreamHandler] = None,
        raw_workers: int = 2,
        eager_when_idle: bool = True,
        buckets: Optional[Sequence[int]] = None,
        host: str = "0.0.0.0",
        batch_threads: int = 4,
    ):
        lib = get_lib()
        if lib is None or not hasattr(lib, "fs_create"):
            raise RuntimeError("native front server library unavailable (make -C native)")
        _bind(lib)
        self._lib = lib
        self.model_fn = model_fn
        self.raw_handler = raw_handler
        self.grpc_handler = grpc_handler
        self.grpc_stream_handler = grpc_stream_handler
        cfg = _FsConfig(
            port=port,
            max_batch=max_batch,
            max_wait_us=int(max_wait_ms * 1000),
            feature_dim=feature_dim,
            out_dim=out_dim,
            stub_mode=1 if (stub and model_fn is None) else 0,
            raw_workers=raw_workers,
            backlog=512,
            eager_when_idle=1 if eager_when_idle else 0,
            batch_threads=batch_threads,
            model_name=model_name.encode(),
            names_csv=",".join(names).encode() if names else b"",
            buckets_csv=",".join(str(int(b)) for b in buckets).encode() if buckets else b"",
            bind_host=host.encode(),
        )
        self._cfg = cfg  # keep the char pointers alive
        self._handle = lib.fs_create(ctypes.byref(cfg))
        self._batch_cb = None
        self._raw_cb = None
        if model_fn is not None:
            self._batch_cb = _BATCH_CB(self._on_batch)
            lib.fs_set_batch_handler(self._handle, self._batch_cb, None)
        if raw_handler is not None:
            self._raw_cb = _RAW_CB(self._on_raw)
            lib.fs_set_raw_handler(self._handle, self._raw_cb, None)
        self._grpc_cb = None
        self._grpc_stream_cb = None
        if grpc_handler is not None and hasattr(lib, "fs_set_grpc_handler"):
            self._grpc_cb = _GRPC_CB(self._on_grpc)
            lib.fs_set_grpc_handler(self._handle, self._grpc_cb, None)
        if grpc_stream_handler is not None and hasattr(lib, "fs_set_grpc_stream_handler"):
            self._grpc_stream_cb = _GRPC_STREAM_CB(self._on_grpc_stream)
            lib.fs_set_grpc_stream_handler(self._handle, self._grpc_stream_cb, None)
        self.port = 0
        self._started = False
        # serialises stop() against set_ready()/stats(): the C++ object
        # must not be destroyed while another thread is inside a call
        self._handle_lock = threading.Lock()

    # ------------------------------------------------------------ callbacks

    def _on_batch(self, _ctx, in_ptr, rows, cols, dtype, out_ptr, out_cols) -> int:
        try:
            # dtype-preserving view: uint8 image payloads reach the
            # model as uint8 (the jit program was warmed for it), f32
            # stays f32 — no host-side cast of the batch
            ctype = ctypes.c_uint8 if dtype == 1 else ctypes.c_float
            typed = ctypes.cast(in_ptr, ctypes.POINTER(ctype))
            batch = np.ctypeslib.as_array(typed, shape=(rows, cols))
            result = np.asarray(self.model_fn(batch), dtype=np.float32)
            if result.ndim == 1:
                result = result[:, None]
            out = np.ctypeslib.as_array(out_ptr, shape=(rows, out_cols))
            out[:] = result.reshape(rows, out_cols)
            return 0
        except Exception:  # a raised callback would abort the C++ worker
            logger.exception("native front server batch callback failed")
            return 1

    def _on_raw(self, _ctx, method, path, body_ptr, body_len, out_buf, out_len,
                status_ptr, ctype_buf) -> int:
        try:
            body = ctypes.string_at(body_ptr, body_len) if body_len else b""
            status, content_type, payload = self.raw_handler(
                method.decode(), path.decode(), body
            )
            buf = self._lib.fs_alloc(len(payload))
            if payload:
                ctypes.memmove(buf, payload, len(payload))
            out_buf[0] = buf
            out_len[0] = len(payload)
            status_ptr[0] = int(status)
            ct = content_type.encode()[:63]
            ctypes.memmove(ctype_buf, ct + b"\x00", len(ct) + 1)
            return 0
        except Exception:  # a raised callback would abort the C++ worker
            logger.exception("native front server raw callback failed")
            return 1

    def _on_grpc(self, _ctx, path, msg_ptr, msg_len, out_buf, out_len,
                 status_ptr, msg_buf) -> int:
        try:
            body = ctypes.string_at(msg_ptr, msg_len) if msg_len else b""
            status, message, payload = self.grpc_handler(path.decode(), body)
            buf = self._lib.fs_alloc(len(payload))
            if payload:
                ctypes.memmove(buf, payload, len(payload))
            out_buf[0] = buf
            out_len[0] = len(payload)
            status_ptr[0] = int(status)
            m = message.encode()[:255]
            ctypes.memmove(msg_buf, m + b"\x00", len(m) + 1)
            return 0
        except Exception:  # a raised callback would abort the C++ worker
            logger.exception("native front server grpc callback failed")
            return 1

    def _on_grpc_stream(self, _ctx, path, msg_ptr, msg_len, handle) -> int:
        try:
            body = ctypes.string_at(msg_ptr, msg_len) if msg_len else b""
            return int(self.grpc_stream_handler(path.decode(), body, int(handle)))
        except Exception:  # a raised callback would abort the C++ worker
            logger.exception("native front server grpc stream callback failed")
            return 1

    # ----------------------------------------------- stream producer API

    def stream_push(self, handle: int, payload: bytes) -> int:
        """Queue one gRPC message on an open server-stream.  Returns -1
        when the stream is dead (client gone) — producers must stop."""
        with self._handle_lock:
            if not self._handle:
                return -1
            buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
            return int(self._lib.fs_stream_push(
                self._handle, ctypes.c_uint64(handle), buf, len(payload)
            ))

    def stream_close(self, handle: int, grpc_status: int = 0,
                     grpc_message: str = "") -> None:
        with self._handle_lock:
            if not self._handle:
                return
            self._lib.fs_stream_close(
                self._handle, ctypes.c_uint64(handle),
                ctypes.c_int32(grpc_status), grpc_message.encode()[:255]
            )

    # ------------------------------------------------------------ lifecycle

    def start(self) -> int:
        rc = self._lib.fs_start(self._handle)
        if rc < 0:
            raise OSError(-rc, "front server failed to start")
        self.port = rc
        self._started = True
        return self.port

    def stop(self) -> None:
        with self._handle_lock:
            handle, self._handle = self._handle, None
            if handle:
                self._lib.fs_stop(handle)
                self._lib.fs_destroy(handle)
            self._started = False

    def set_ready(self, ready: bool) -> None:
        with self._handle_lock:
            if self._handle:
                self._lib.fs_set_ready(self._handle, 1 if ready else 0)

    def stats(self) -> dict:
        s = _FsStats()
        with self._handle_lock:
            if self._handle:
                self._lib.fs_get_stats(self._handle, ctypes.byref(s))
        return {name: getattr(s, name) for name, _ in _FsStats._fields_}

    def __enter__(self) -> "NativeFrontServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


class GatewayRawHandler:
    """Fallback-lane handler speaking full engine semantics.

    Bridges the C++ server's raw lane into a running Gateway's asyncio
    loop: predictions with exotic payloads, feedback, explanations.
    """

    def __init__(self, gateway, loop):
        self.gateway = gateway
        self.loop = loop

    @staticmethod
    def _payload(body: bytes, query: dict) -> dict:
        """JSON body, form-encoded ``json`` field, or ``?json=`` query —
        the Python app's _request_body semantics (runtime/rest.py)."""
        if body:
            try:
                return json.loads(body)
            except (json.JSONDecodeError, UnicodeDecodeError):
                from urllib.parse import parse_qs as _pq

                try:
                    form = _pq(body.decode(), strict_parsing=True)
                except (UnicodeDecodeError, ValueError):
                    form = {}
                if "json" in form:
                    return json.loads(form["json"][0])
                raise ValueError("invalid JSON body")
        if "json" in query:
            return json.loads(query["json"][0])
        raise ValueError("empty request body")

    # request targets eligible for the buffer-view (SRT1) lane
    _PREDICT_PATHS = ("/api/v0.1/predictions", "/api/v1.0/predictions", "/predict")

    def _frame_lane_service(self, predictor):
        """The predictor eligible for the loop-free frame paths, or
        None (shadows / traffic splits / named-predictor routing keep
        full gateway semantics)."""
        if predictor is None and len(self.gateway.entries) == 1 \
                and not self.gateway.shadows:
            return self.gateway.entries[0][0]
        return None

    def _predict_raw_frame(self, body: bytes, predictor) -> Tuple[int, str, bytes]:
        """The zero-copy lane: an SRT1 frame body decodes to a
        :class:`~seldon_core_tpu.codec.BufferView` over the ingress
        bytes (no JSON/proto parse, no python lists, no float64
        widening), rides the engine as a by-reference payload, and the
        response array leaves as an SRT1 frame.  Full engine semantics
        — deadlines, breakers, tracing, shedding — are untouched: only
        the payload codec changed.  Errors keep the JSON status shape
        (clients tell the lanes apart by Content-Type, exactly like the
        C++ fast lane).

        A MULTI-frame container (``pack_frames``: N tensors, 8-byte
        aligned) is the batched-submission surface: the whole container
        goes through ``raw_batch_views`` as ONE stacked micro-batch —
        per-request engine bookkeeping is bypassed exactly like the
        in-C++ fast lane, so it is only served for single-local-MODEL
        deployments — and the reply is the response container."""
        import asyncio

        from seldon_core_tpu import codec
        from seldon_core_tpu.engine.server import _http_status
        from seldon_core_tpu.runtime.message import InternalMessage

        views = codec.unpack_frames(body)
        svc = self._frame_lane_service(predictor)
        fast = svc.single_local_model() if svc is not None else None
        if len(views) > 1:
            raw_views = getattr(fast[1], "raw_batch_views", None) if fast else None
            if raw_views is None:
                return 400, "application/json", json.dumps(
                    {"status": {"status": "FAILURE", "code": 400,
                                "info": "multi-frame containers need a "
                                        "single-local-MODEL predictor with "
                                        "raw_batch_views; send one frame "
                                        "per request",
                                "reason": "BAD_REQUEST"}}
                ).encode()
            outs = raw_views(views)
            return 200, "application/x-seldon-raw", codec.pack_frames(outs)
        msg = InternalMessage(payload=views[0], kind="rawTensor")
        if fast is not None:
            # single-local-MODEL deployment: run the graph ON this C++
            # raw-worker thread (predict_sync — the sync gRPC server's
            # fast path), so the frame lane never crosses the event
            # loop.  Shadows / traffic splits / multi-node graphs take
            # the full async gateway below.
            out = svc.predict_sync(msg)
        else:
            out = asyncio.run_coroutine_threadsafe(
                self.gateway.predict(msg, predictor=predictor), self.loop
            ).result(timeout=60)
        status = _http_status(out)
        payload = out.host_payload()
        if status < 400 and payload is not None and not isinstance(
            payload, (bytes, str, dict)
        ):
            try:
                return status, "application/x-seldon-raw", codec.pack_frame(
                    np.asarray(payload)
                )
            except codec.PayloadError:
                # a healthy answer whose dtype has no SRT1 code
                # (strings/objects): degrade to the JSON reply below
                pass
        return status, "application/json", json.dumps(out.to_json()).encode()

    def __call__(self, method: str, path: str, body: bytes) -> Tuple[int, str, bytes]:
        import asyncio
        from urllib.parse import parse_qs, urlsplit

        from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage

        try:
            # the C++ lane forwards the full target; split off the query
            # so '?predictor=NAME' routing matches the Python app
            split = urlsplit(path)
            path = split.path
            query = parse_qs(split.query)
            predictor = (query.get("predictor") or [None])[0]
            if (
                method == "POST"
                and path in self._PREDICT_PATHS
                and body[:4] == b"SRT1"
            ):
                from seldon_core_tpu.codec import bufview

                if bufview.zero_copy_enabled():
                    return self._predict_raw_frame(body, predictor)
                # lane off: the frame is not a JSON body — reject it the
                # way the JSON parser would, naming the remedy
                return 400, "application/json", json.dumps(
                    {"status": {"status": "FAILURE", "code": 400,
                                "info": "SRT1 frame received but "
                                        "SELDON_TPU_ZERO_COPY=0 — send a "
                                        "JSON SeldonMessage",
                                "reason": "BAD_REQUEST"}}
                ).encode()
            if path in ("/pause", "/unpause") and method in ("POST", "PUT"):
                # synchronous flag flips; we are already off the loop on a
                # C++ raw-worker thread, so call directly
                (self.gateway.pause if path == "/pause" else self.gateway.unpause)()
                return 200, "text/plain", (path[1:] + "d").encode()
            if path in ("/api/v0.1/predictions", "/api/v1.0/predictions", "/predict"):
                msg = InternalMessage.from_json(self._payload(body, query))
                out = asyncio.run_coroutine_threadsafe(
                    self.gateway.predict(msg, predictor=predictor), self.loop
                ).result(timeout=60)
            elif path == "/api/v0.1/feedback":
                fb = InternalFeedback.from_json(self._payload(body, query))
                out = asyncio.run_coroutine_threadsafe(
                    self.gateway.send_feedback(fb), self.loop
                ).result(timeout=60)
            elif path == "/api/v0.1/explanations":
                msg = InternalMessage.from_json(self._payload(body, query))
                svc = (self.gateway.by_name(predictor) if predictor else None) or self.gateway.pick()
                out = asyncio.run_coroutine_threadsafe(
                    svc.explain(msg), self.loop
                ).result(timeout=60)
            else:
                return 404, "application/json", json.dumps(
                    {"status": {"status": "FAILURE", "code": 404,
                                "info": f"no route {path}", "reason": "NOT_FOUND"}}
                ).encode()
            from seldon_core_tpu.engine.server import _http_status

            return _http_status(out), "application/json", json.dumps(out.to_json()).encode()
        except (ValueError, KeyError, TypeError) as e:
            # bad payloads are the client's fault: 400, matching the app
            return 400, "application/json", json.dumps(
                {"status": {"status": "FAILURE", "code": 400, "info": str(e),
                            "reason": "BAD_REQUEST"}}
            ).encode()
        except Exception as e:  # noqa: BLE001 — wire errors as seldon status
            logger.exception("gateway raw handler failed")
            return 500, "application/json", json.dumps(
                {"status": {"status": "FAILURE", "code": 500, "info": str(e),
                            "reason": "ENGINE_ERROR"}}
            ).encode()


def pack_raw_frame(arr: np.ndarray) -> bytes:
    """Encode an array as the binary raw-tensor frame (SRT1).

    Delegates to the buffer-view codec — ONE framing implementation
    (codec/bufview.py) shared with the zero-copy lane, so the C++
    parser, the load clients and the Python lane cannot drift."""
    from seldon_core_tpu.codec import bufview

    return bufview.pack_frame(np.asarray(arr))


def native_load(
    port: int,
    payload: bytes,
    seconds: float = 5.0,
    connections: int = 8,
    depth: int = 8,
) -> Optional[dict]:
    """Closed-loop load from the C++ epoll client (``native/loadgen.cc``).

    ``payload`` is a complete HTTP/1.1 request blob sent over
    ``connections`` keep-alive loopback sockets with ``depth`` requests
    in flight each.  Returns ``{qps, ok, non2xx, errors}`` or None when
    the native library (or ``lg_run``) is unavailable.  The reference
    keeps its load generator off the benched host entirely (64 Locust
    slaves on 3 nodes, reference: benchmarking.md:31-34); this is the
    single-host equivalent — a client cheap enough that the measured
    number is the server's.
    """
    if seconds <= 0:
        raise ValueError(f"seconds must be positive, got {seconds}")
    lib = get_lib()
    if lib is None or not hasattr(lib, "lg_run"):
        return None
    lib.lg_run.restype = ctypes.c_int64
    lib.lg_run.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_double,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    non2xx = ctypes.c_int64(0)
    errors = ctypes.c_int64(0)
    ok = lib.lg_run(
        payload, len(payload), int(port), float(seconds),
        int(connections), int(depth),
        ctypes.byref(non2xx), ctypes.byref(errors),
    )
    return {
        "qps": ok / seconds,
        "ok": int(ok),
        "non2xx": int(non2xx.value),
        "errors": int(errors.value),
    }


def build_grpc_request_parts(path: str, proto_bytes: bytes,
                             authority: str = "localhost") -> Tuple[bytes, bytes]:
    """(HPACK header block, gRPC-framed DATA payload) for the h2c load
    client (``lg_run_h2``).  Static indexes for :method POST / :scheme
    http; everything else as raw never-indexed literals — exactly the
    subset the C++ lane's HPACK decoder handles without state."""

    def lit(name: bytes, value: bytes) -> bytes:
        def ln(n: int) -> bytes:
            if n < 127:
                return bytes([n])
            out = bytearray([127])
            v = n - 127
            while v >= 128:
                out.append(0x80 | (v & 0x7F))
                v >>= 7
            out.append(v)
            return bytes(out)

        return b"\x10" + ln(len(name)) + name + ln(len(value)) + value

    block = (
        b"\x83"  # :method POST (static 3)
        + b"\x86"  # :scheme http (static 6)
        + lit(b":path", path.encode())
        + lit(b":authority", authority.encode())
        + lit(b"content-type", b"application/grpc")
        + lit(b"te", b"trailers")
    )
    data = b"\x00" + len(proto_bytes).to_bytes(4, "big") + proto_bytes
    return block, data


def native_load_grpc(
    port: int,
    path: str,
    proto_bytes: bytes,
    seconds: float = 5.0,
    connections: int = 8,
    depth: int = 8,
) -> Optional[dict]:
    """Closed-loop gRPC (h2c) load against the native ingress — the
    counterpart of :func:`native_load` for the contract surface the
    reference's engine serves natively (SeldonGrpcServer.java:30-60)."""
    if seconds <= 0:
        raise ValueError(f"seconds must be positive, got {seconds}")
    lib = get_lib()
    if lib is None or not hasattr(lib, "lg_run_h2"):
        return None
    lib.lg_run_h2.restype = ctypes.c_int64
    lib.lg_run_h2.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_double, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    block, data = build_grpc_request_parts(path, proto_bytes)
    non2xx = ctypes.c_int64(0)
    errors = ctypes.c_int64(0)
    ok = lib.lg_run_h2(
        block, len(block), data, len(data), int(port), float(seconds),
        int(connections), int(depth),
        ctypes.byref(non2xx), ctypes.byref(errors),
    )
    return {
        "qps": ok / seconds,
        "ok": int(ok),
        "non2xx": int(non2xx.value),
        "errors": int(errors.value),
    }


class StaleConnection(ConnectionError):
    """A reused keep-alive socket was closed by the peer before any
    response byte — the one case a client may transparently retry."""


def read_http_response(sock, buf: bytes, timeout_s: Optional[float] = None):
    """Blocking HTTP/1.1 response read on a keep-alive socket.

    Returns (status_code, body, remaining_buffer).  Raises
    StaleConnection when the peer closed before ANY byte arrived — a
    clean FIN *or* an RST (the usual idle-keep-alive race: a small send
    lands in the kernel buffer after the peer's FIN, the peer answers
    RST, and recv fails with ConnectionResetError before any response
    byte) — safe to retry on a fresh connection.  ConnectionError on
    mid-response close/reset.  Shared by the SDK's RawFrameClient and
    the bench's native-front workers so the parsing logic cannot drift.
    """
    if timeout_s is not None:
        sock.settimeout(timeout_s)
    got_any = bool(buf)

    def _recv():
        nonlocal got_any
        try:
            chunk = sock.recv(65536)
        except ConnectionResetError as e:
            if not got_any:
                raise StaleConnection("peer reset an idle keep-alive socket") from e
            raise ConnectionError("server reset mid-response") from e
        if chunk:
            got_any = True
        return chunk

    while b"\r\n\r\n" not in buf:
        chunk = _recv()
        if not chunk:
            if not got_any:
                raise StaleConnection("peer closed an idle keep-alive socket")
            raise ConnectionError("server closed mid-response")
        buf += chunk
    headers, _, rest = buf.partition(b"\r\n\r\n")
    status = int(headers.split(b" ", 2)[1])
    length = None
    for line in headers.split(b"\r\n"):
        if line.lower().startswith(b"content-length"):
            length = int(line.split(b":")[1])
            break
    if length is None:
        raise ConnectionError("response carries no Content-Length")
    while len(rest) < length:
        chunk = _recv()
        if not chunk:
            raise ConnectionError("server closed mid-body")
        rest += chunk
    return status, rest[:length], rest[length:]


def unpack_raw_frame(data: bytes) -> np.ndarray:
    """Decode a binary raw-tensor frame (SRT1) into an array (a
    zero-copy view over ``data`` — see codec/bufview.py)."""
    from seldon_core_tpu.codec import bufview

    return bufview.unpack_frame(data).array()
