"""ctypes bindings for the native C++ data-plane core.

Loads ``libseldon_tpu_native.so`` (built by ``make native``; also
auto-built on first import when a toolchain is present) and exposes the
codec hot loops.  Every function has a pure-Python fallback, so the
framework runs unchanged without the library — native just makes the
1-CPU REST path faster.
"""

from __future__ import annotations

import base64 as _pyb64
import ctypes
import json as _pyjson
import logging
import os
import subprocess
from typing import List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _so_path() -> str:
    # SELDON_TPU_NATIVE_SO overrides the artifact (e.g. the TSan/ASan
    # builds from `make -C native tsan`)
    from seldon_core_tpu.runtime import knobs

    override = knobs.raw("SELDON_TPU_NATIVE_SO")
    if override:
        return override
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
                        "native", "libseldon_tpu_native.so")


# the shared library's inputs (keep in sync with SRCS in native/Makefile;
# other .cc files there — e.g. remote_node.cc — build separate binaries)
_LIB_SOURCES = ("codec.cc", "frontserver.cc", "h2grpc.cc", "h2grpc.h",
                "loadgen.cc", "Makefile")


def _is_stale(so: str) -> bool:
    """True when the .so is missing or older than one of its sources —
    a stale artifact would load with a mismatched struct ABI."""
    if not os.path.exists(so):
        return True
    so_mtime = os.path.getmtime(so)
    src_dir = os.path.dirname(so)
    for name in _LIB_SOURCES:
        path = os.path.join(src_dir, name)
        if os.path.exists(path) and os.path.getmtime(path) > so_mtime:
            return True
    return False


def _build_if_stale(so: str) -> None:
    """Must be called with the build lock held."""
    if not _is_stale(so):
        return
    makefile_dir = os.path.dirname(so)
    if not os.path.exists(os.path.join(makefile_dir, "Makefile")):
        return
    try:
        subprocess.run(
            ["make", "-C", makefile_dir], check=True, capture_output=True, timeout=120
        )
    except Exception as e:  # noqa: BLE001 — opportunistic rebuild; the
        # load path reports the real failure
        logger.debug("native build failed: %s", e)


class _BuildLock:
    """flock serializing build AND load: many microservice processes can
    start at once (ReplicaSet scale-up); an unlocked staleness fast-path
    could see a half-linked .so with a fresh mtime and dlopen garbage,
    so dlopen also happens under the lock."""

    def __init__(self, so: str):
        self._dir = os.path.dirname(so)
        self._fh = None

    def __enter__(self):
        try:
            import fcntl

            self._fh = open(os.path.join(self._dir, ".build.lock"), "w")
            fcntl.flock(self._fh, fcntl.LOCK_EX)
        except Exception as e:  # noqa: BLE001 — e.g. read-only install dir
            logger.debug("native build lock unavailable: %s", e)
            self._fh = None
        return self

    def __exit__(self, *exc):
        if self._fh is not None:
            self._fh.close()  # releases the flock
            self._fh = None
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    so = _so_path()
    with _BuildLock(so):
        _LIB = _load(so)
    return _LIB


def _load(so: str) -> Optional[ctypes.CDLL]:
    _build_if_stale(so)
    if not os.path.exists(so):
        return None
    try:
        lib = ctypes.CDLL(so)
        lib.b64_encoded_len.restype = ctypes.c_int64
        lib.b64_encoded_len.argtypes = [ctypes.c_int64]
        lib.b64_encode.restype = ctypes.c_int64
        lib.b64_encode.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p]
        lib.b64_decode.restype = ctypes.c_int64
        lib.b64_decode.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p]
        lib.json_parse_f64.restype = ctypes.c_int64
        lib.json_parse_f64.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ]
        lib.json_serialize_f64.restype = ctypes.c_int64
        lib.json_serialize_f64.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_char_p,
        ]
        lib.batch_gather_pad.restype = None
        lib.batch_gather_pad.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
        ]
        # v3 added the srt1_* framing-agreement surface (zero-copy
        # lane); v4 the CRC32C integrity-trailer twins
        if lib.native_abi_version() != 4:  # not assert: must survive python -O
            raise RuntimeError(
                "stale libseldon_tpu_native.so (ABI mismatch): rebuild with `make -C native`"
            )
        lib.srt1_item_size.restype = ctypes.c_int64
        lib.srt1_item_size.argtypes = [ctypes.c_int32]
        lib.srt1_header_bytes.restype = ctypes.c_int64
        lib.srt1_header_bytes.argtypes = [ctypes.c_int32]
        lib.srt1_magic.restype = ctypes.c_uint32
        lib.srt1_payload_bytes.restype = ctypes.c_int64
        lib.srt1_payload_bytes.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ]
        lib.srt1_crc_magic.restype = ctypes.c_uint32
        lib.srt1_crc32c.restype = ctypes.c_uint32
        # c_char_p: python bytes pass by POINTER (no staging copy) —
        # the checksum runs twice per multi-MB KV container during
        # evacuation, exactly when time and memory are tightest
        lib.srt1_crc32c.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ]
        logger.info("native data-plane core loaded from %s", so)
        return lib
    except Exception as e:  # noqa: BLE001 — missing native core degrades
        # to the python lane, never kills serving
        logger.warning("failed to load native core: %s", e)
        return None


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# base64
# ---------------------------------------------------------------------------

def b64encode(data: bytes) -> str:
    lib = get_lib()
    if lib is None:
        return _pyb64.b64encode(data).decode("ascii")
    out = ctypes.create_string_buffer(int(lib.b64_encoded_len(len(data))))
    n = lib.b64_encode(data, len(data), out)
    return out.raw[:n].decode("ascii")


def b64decode(text: str) -> bytes:
    lib = get_lib()
    if lib is None:
        return _pyb64.b64decode(text)
    raw = text.encode("ascii")
    out = ctypes.create_string_buffer(len(raw))
    n = lib.b64_decode(raw, len(raw), out)
    if n < 0:
        raise ValueError("malformed base64")
    return out.raw[:n]


# ---------------------------------------------------------------------------
# JSON number arrays
# ---------------------------------------------------------------------------

def parse_f64_array(text: str) -> np.ndarray:
    """Flat parse of a (possibly nested) JSON number array."""
    lib = get_lib()
    if lib is None:
        return np.asarray(_pyjson.loads(text), dtype=np.float64).ravel()
    raw = text.encode("ascii")
    cap = max(1, raw.count(b",") + raw.count(b"[") + 2)
    out = np.empty(cap, dtype=np.float64)
    n = lib.json_parse_f64(raw, len(raw),
                           out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), cap)
    if n < 0:
        raise ValueError("malformed JSON number array")
    return out[:n].copy()


def serialize_f64_array(arr: np.ndarray) -> str:
    """Flat JSON serialisation of a float64 array."""
    lib = get_lib()
    flat = np.ascontiguousarray(arr, dtype=np.float64).ravel()
    if lib is None:
        return _pyjson.dumps(flat.tolist())
    out = ctypes.create_string_buffer(int(flat.size) * 26 + 2)
    n = lib.json_serialize_f64(flat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                               flat.size, out)
    return out.raw[:n].decode("ascii")


# ---------------------------------------------------------------------------
# batch assembly
# ---------------------------------------------------------------------------

def gather_pad(arrays: Sequence[np.ndarray], bucket_rows: int) -> np.ndarray:
    """Concatenate row batches and zero-pad to `bucket_rows` in one pass."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    first = arrays[0]
    row_shape = first.shape[1:]
    dtype = first.dtype
    lib = get_lib()
    if lib is None:
        total = sum(a.shape[0] for a in arrays)
        batch = np.concatenate(arrays, axis=0) if len(arrays) > 1 else first
        if total < bucket_rows:
            pad = [(0, bucket_rows - total)] + [(0, 0)] * (batch.ndim - 1)
            batch = np.pad(batch, pad)
        return batch
    row_bytes = int(np.prod(row_shape)) * dtype.itemsize
    out = np.empty((bucket_rows, *row_shape), dtype=dtype)
    k = len(arrays)
    srcs = (ctypes.c_char_p * k)(
        *[ctypes.cast(ctypes.c_void_p(a.ctypes.data), ctypes.c_char_p) for a in arrays]
    )
    rows = (ctypes.c_int64 * k)(*[a.shape[0] for a in arrays])
    lib.batch_gather_pad(srcs, rows, k, row_bytes, bucket_rows,
                         out.ctypes.data_as(ctypes.c_char_p))
    return out
